"""Round-long opportunistic TPU sampler (VERDICT r4 item 1).

The axon TPU tunnel on this box is flaky: it can be down for hours and a
round-end one-shot bench then records a CPU fallback (rounds 1-4 all lost
their headline device number this way).  This watcher turns device
sampling into a round-long process instead of a round-end event:

* every PROBE_INTERVAL seconds, probe the backend in a bounded
  subprocess (``bench.py --probe`` — the parent never imports jax);
* the moment the probe reports a live TPU, run the headline pallas
  ladder (32768 first) and then the BASELINE configs 2/5/3, each in its
  own watchdog-bounded subprocess;
* persist every successful device measurement as one JSON line in
  ``benchmarks/device_runs.jsonl`` (timestamp, metric, value, device,
  provenance) — ``bench.py`` reports the freshest entry when its own
  live attempt can't reach the device;
* after a full sweep, keep refreshing the cheap headline number each
  uptime window so the freshest entry stays recent, and log every
  probe so a tunnel that never comes up leaves evidence (the probe
  log, e.g. ``benchmarks/watcher_r5.log`` — parsed into bench.py's
  ``watcher_evidence`` artifact field);
* fold each rotated-away round's banked samples into per-kind median
  rows in ``benchmarks/bench_history.jsonl`` and flag any fresh sample
  falling beyond the last rounds' spread as a ``kind="regression"`` row
  + ``bench.regression`` event (ISSUE 16) — a silent perf cliff
  surfaces in the round it happens.  With ``TPUNODE_PROFILE_DIR`` set,
  workers capture a device profile per banked run and the verdict rows
  carry its path (``profile_path``).

Single-core box discipline: when the tunnel is down the watcher is a
sleeping process plus one network-blocked probe subprocess — no CPU
burned while the builder's tests run in the foreground.

Run detached from the repo root (round start):

    nohup python -m benchmarks.watcher >> benchmarks/watcher_r5.log 2>&1 &

For a MID-ROUND relaunch (watcher died / code updated) add
``TPUNODE_WATCHER_KEEP_RUNS=1`` so already-banked in-round samples are
kept instead of rotated away; a pidfile guard (.watcher_pid) refuses to
start a second concurrent watcher either way.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from benchmarks.common import (  # noqa: E402
    run_json_subprocess,
    worker_rung_env,
)

RUNS_PATH = os.path.join(REPO, "benchmarks", "device_runs.jsonl")
PREV_RUNS_PATH = RUNS_PATH + ".prev"

# Cross-round BENCH history (ISSUE 16): at each round rotation the
# rotated-away round's banked samples are folded into ONE per-kind
# median row here, and every fresh in-round sample is compared against
# the last HISTORY_ROUNDS rounds' medians — a sample falling beyond the
# historical spread is flagged as a kind="regression" row plus a
# bench.regression event, so a silent perf cliff (kernel change, tunnel
# degradation) surfaces in the round summary instead of months later.
HISTORY_PATH = os.path.join(REPO, "benchmarks", "bench_history.jsonl")
HISTORY_ROUNDS = 5
# Below (median - max(spread, MIN_BAND*median)) flags: the band floor
# keeps a tightly-clustered history (spread ~0) from flagging noise.
REGRESSION_MIN_BAND = 0.05

# Uptime windows can be ~9 min (observed r5): a 240s gap between probes
# could eat half a window, so probe every 150s (each probe is mostly a
# network-blocked subprocess; ~3s of CPU for the jax import).
PROBE_INTERVAL = float(os.environ.get("TPUNODE_WATCHER_PROBE_INTERVAL", 150))
PROBE_TIMEOUT = float(os.environ.get("TPUNODE_WATCHER_PROBE_TIMEOUT", 150))
# After a fully-successful sweep, re-probe less often and only refresh the
# cheap headline (the compile caches are warm by then).
REFRESH_INTERVAL = float(os.environ.get("TPUNODE_WATCHER_REFRESH_INTERVAL", 900))
DEADLINE_S = float(os.environ.get("TPUNODE_WATCHER_DEADLINE_S", 11.0 * 3600))

# Outside the driver's round-end window we can afford generous watchdogs:
# a server-side compile that outlives one attempt is found warm by the next.
# (batch, budget, kernel): kernel None = auto (pallas on TPU); "xla" rungs
# are the fallback for a Mosaic/remote-compile outage (observed r5: the
# axon compile helper 500s on every pallas program while plain XLA
# compiles and runs) — a broken-pallas uptime window must still bank a
# device headline and unlock the config sweep.
LADDER = (
    (32768, 360.0, None),
    (8192, 180.0, None),
    (4096, 150.0, None),
    (16384, 420.0, "xla"),
    (8192, 300.0, "xla"),
    (4096, 240.0, "xla"),
)
# Until the round's FIRST headline is banked, lead with the
# fast-compiling XLA rungs instead of gambling a short uptime window on
# the 360 s pallas compile: the observed r5 window (03:48-03:54Z) was
# burned entirely by one hanging pallas compile, and ANY banked device
# number beats an empty artifact (VERDICT r4 item 1).  8192 first — its
# compile is quick and its throughput is already at the XLA plateau
# (PERF.md r3 table); after an XLA bank, main() immediately re-runs the
# ladder pallas-only in the same window (the upgrade attempt), and the
# pallas rungs below only run directly if every XLA rung failed.
FIRSTBANK_LADDER = (
    (8192, 300.0, "xla"),
    (4096, 240.0, "xla"),
    (32768, 360.0, None),
    (8192, 180.0, None),
    (16384, 420.0, "xla"),
)
# Affine point-form rungs (ISSUE 8): once per round after the configs,
# bank a device number for the new formulation (kind="affine" rows —
# bench.py's headline fallback ignores them, so a slower affine sample
# can never mask the projective headline).  The pallas rung leads; the
# XLA rung is the Mosaic-outage fallback, same discipline as LADDER.
AFFINE_LADDER = (
    (32768, 360.0, None),
    (8192, 300.0, "xla"),
)
# Lazy-reduction rungs (ISSUE 12): once per round after the affine slot,
# bank a device number for the lazy pipeline (kind="lazy" rows — the
# headline fallback ignores them).  The combined lazy+5-bit-window rung
# leads (the full formulation the roofline model favors); the lazy-only
# XLA rung is the Mosaic-outage fallback.
LAZY_LADDER = (
    (32768, 360.0, None, "lazy", 5),
    (32768, 360.0, None, "lazy", 4),
    (8192, 300.0, "xla", "lazy", 4),
)
# Pod-mesh rungs (ISSUE 13): once per round after the lazy slot, bank a
# device number for 8/4/2-way sharded dispatch (kind="mesh" rows — the
# headline fallback ignores them; bench.py --mesh-device clamps the way
# count to the visible devices and reports the actual).  (ways, budget,
# kernel): kernel None = auto (pallas on TPU); the XLA retry below is
# the Mosaic-outage fallback, same discipline as the other experiment
# ladders.
MESH_LADDER = (
    (8, 360.0, None),
    (4, 300.0, None),
    (2, 240.0, None),
)
CONFIG_BUDGETS = {"config2": 600.0, "config5": 900.0, "config3": 900.0}
# Observability-overhead slot (ISSUE 16/17): the worker is jax-free and
# CPU-pinned, so the budget only covers interpreter start + micro-bench.
OBS_BUDGET = float(os.environ.get("TPUNODE_WATCHER_OBS_BUDGET", 120))
# Host-affine feed A/B slot (ISSUE 19): two 4-way cpu-native e2e legs
# plus the campaign pass — jax-free like the observability slot, but
# each leg carries real native verification, so the budget matches the
# bench driver's section budget.
MESH_E2E_BUDGET = float(
    os.environ.get("TPUNODE_WATCHER_MESH_E2E_BUDGET", 240)
)
# Multi-tenant serve firehose slot (ISSUE 20): >=1000 real-socket
# clients against a live ServeServer on the cpu-native proxy — jax-free
# like the mesh_e2e slot, same budget shape as the bench driver's
# section budget.
SERVE_BUDGET = float(os.environ.get("TPUNODE_WATCHER_SERVE_BUDGET", 240))
# Sweep order: config2 is cheap; config3 (full-node IBD on device) is
# the VERDICT item-2 money shot and must be banked before config5,
# whose ~150k-sig batch is the slowest compile during an outage.  One
# constant drives both the sweep loop and the all-banked cadence check.
CONFIG_ORDER = ("config2", "config3", "config5")


def _log(msg: str) -> None:
    print(f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] {msg}",
          flush=True)


def _history_key(kind: str, payload: dict) -> str:
    """Series key for cross-round comparison.  Mesh rows bank several
    way-counts per round with very different totals (8-way vs 2-way);
    mixing them would inflate the spread until nothing ever flags, so
    the way-count is part of the key."""
    ways = payload.get("mesh_ways")
    return f"{kind}@{ways}w" if ways else kind


def _fold_history(rows: list[dict]) -> None:
    """Append one per-kind median row for a rotated-away round's banked
    samples.  Best-effort: a history write failure must never block the
    rotation (the runs file is the artifact of record)."""
    by_key: dict[str, list[float]] = {}
    for row in rows:
        v = row.get("value")
        kind = row.get("kind")
        if kind and kind != "regression" and isinstance(v, (int, float)):
            by_key.setdefault(_history_key(kind, row), []).append(float(v))
    if not by_key:
        return
    hist = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "unix": int(time.time()),
            "medians": {k: round(statistics.median(vs), 3)
                        for k, vs in sorted(by_key.items())}}
    try:
        with open(HISTORY_PATH, "a", encoding="utf-8") as f:
            f.write(json.dumps(hist) + "\n")
        _log(f"folded round history: {len(by_key)} series "
             f"-> {HISTORY_PATH}")
    except OSError:
        pass


def _load_history(n: int = HISTORY_ROUNDS) -> list[dict]:
    """Last ``n`` per-round median rows (oldest first); [] when absent."""
    try:
        with open(HISTORY_PATH, encoding="utf-8") as f:
            rows = []
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(row, dict) and isinstance(
                    row.get("medians"), dict
                ):
                    rows.append(row)
    except OSError:
        return []
    return rows[-n:]


def detect_regression(
    key: str, value: float, history: list[dict]
) -> dict | None:
    """Flag ``value`` when it falls below the historical band for
    ``key``: past rounds' medians' median, minus the larger of their
    spread and a 5% floor.  Needs >=3 rounds of history (one or two
    medians give no spread estimate worth alarming on).  Returns the
    regression payload, or None when the sample is in-band."""
    meds = [float(h["medians"][key]) for h in history
            if isinstance(h["medians"].get(key), (int, float))]
    if len(meds) < 3:
        return None
    center = statistics.median(meds)
    if center <= 0:
        return None
    spread = max(meds) - min(meds)
    floor = center - max(spread, REGRESSION_MIN_BAND * center)
    if value >= floor:
        return None
    return {
        "key": key, "value": round(value, 3),
        "baseline": round(center, 3), "spread": round(spread, 3),
        "floor": round(floor, 3), "rounds": len(meds),
        "drop_pct": round(100.0 * (center - value) / center, 1),
    }


def _check_regression(kind: str, payload: dict) -> None:
    """Compare a freshly-banked sample against the cross-round history;
    called from _record for every row EXCEPT regression rows themselves
    (no self-feedback).  A flag is both a kind="regression" row (lands
    in the round summary with the rest of the runs file) and a
    bench.regression event (the in-process observability channel)."""
    v = payload.get("value")
    if not isinstance(v, (int, float)):
        return
    reg = detect_regression(
        _history_key(kind, payload), float(v), _load_history()
    )
    if reg is None:
        return
    _log(f"REGRESSION {reg['key']}: {reg['value']} vs baseline "
         f"{reg['baseline']} (-{reg['drop_pct']}%, floor {reg['floor']})")
    _record("regression", reg)
    try:
        from tpunode.events import events  # stdlib-only import, kept lazy
        events.emit("bench.regression", **reg)
    except Exception:
        pass  # the runs-file row is the artifact of record


def _record(kind: str, payload: dict) -> None:
    row = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "unix": int(time.time()), "kind": kind}
    row.update(payload)
    with open(RUNS_PATH, "a", encoding="utf-8") as f:
        f.write(json.dumps(row) + "\n")
    _log(f"recorded {kind}: value={payload.get('value')} "
         f"device={payload.get('device')}")
    if kind != "regression":
        _check_regression(kind, payload)


def _run_json(argv: list[str], timeout: float,
              env_extra: dict | None = None) -> dict:
    return run_json_subprocess(argv, timeout, env_extra, cwd=REPO)


def probe() -> dict:
    return _run_json([sys.executable, "bench.py", "--probe"], PROBE_TIMEOUT)


class FatalMismatch(RuntimeError):
    """Device/oracle verdict mismatch observed by the watcher."""


# Tunnel uptime windows are short (observed r5: ~6-9 min).  Once a sweep
# sees the Mosaic compile helper broken, later sweeps keep only ONE
# short pallas probe rung (a still-broken helper MosaicErrors in ~45s;
# a recovered one benefits from the server-side compile surviving the
# kill) before the XLA rungs, so an uptime window banks a headline
# instead of burning on doomed compiles.
_mosaic_broken = False
# Set after the first banked headline: later sweeps chase the pallas
# number; until then FIRSTBANK_LADDER banks the quickest device number.
_headline_banked = False
# AFFINE-program-only Mosaic/timeout failures (ISSUE 8 rungs): kept
# separate from _mosaic_broken so an experiment that Mosaic can't lower
# never degrades the projective headline ladder (review r8).
_affine_pallas_broken = False
# Same isolation for the LAZY-program rungs (ISSUE 12): the lazy/5-bit
# programs carry constructs Mosaic may reject (47-sublane wides,
# 32-entry tables — mosaic_diag's lazy_reduce/window5 cases) while the
# eager flagship lowers fine.
_lazy_pallas_broken = False
# And for the MESH rungs (ISSUE 13): pallas-inside-shard_map may break
# independently of the flagship single-chip program.
_mesh_pallas_broken = False

BENCH_LOCK = os.path.join(REPO, "benchmarks", ".bench_running")


def _bench_running() -> bool:
    """The driver's round-end bench holds the tunnel exclusively (clients
    block each other) — checked between probes AND between rungs, so a
    bench that starts mid-sweep isn't starved by our workers."""
    try:
        return time.time() - os.path.getmtime(BENCH_LOCK) < 1800
    except OSError:
        return False


def run_headline(
    pallas_only: bool = False,
) -> tuple[dict | None, str, bool]:
    """Device ladder: XLA-first until a headline is banked this round,
    pallas 32768-first after.  Returns ``(worker_dict, "banked",
    pallas_failed)`` on success, or ``(None, reason, pallas_failed)``
    with reason one of ``"exhausted"`` (device live, every rung failed —
    worth diagnosing), ``"yielded"`` (bench.py took the tunnel) or
    ``"tunnel-lost"`` (the uptime window closed mid-sweep) — the caller
    must NOT run more tunnel clients for the last two.

    ``pallas_failed`` (ADVICE r5 #1) reports whether any pallas rung was
    attempted AND failed during this sweep — with a Mosaic error or
    otherwise (e.g. worker OOM, which doesn't set the broken flag).  The
    caller uses it to skip the same-window pallas-only upgrade when the
    banking sweep just proved those exact rungs failing: re-running them
    would burn up to ~540 s of a ~6-9 min uptime window before the
    config sweep.  Raises FatalMismatch on a device/oracle verdict
    mismatch.

    ``pallas_only``: the same-window upgrade attempt after an XLA
    first-bank — only the pallas rungs are worth running (an XLA number
    is already on disk)."""
    global _mosaic_broken, _headline_banked, _affine_pallas_broken
    global _lazy_pallas_broken
    if pallas_only:
        rungs = [r for r in LADDER if r[2] is None]
    elif _mosaic_broken:
        rungs = ([(32768, 150.0, None)]
                 + [r for r in LADDER if r[2] == "xla"])
    elif not _headline_banked:
        rungs = list(FIRSTBANK_LADDER)
    else:
        rungs = list(LADDER)
    pallas_failed = False
    while rungs:
        if _bench_running():
            _log("bench.py started mid-sweep — yielding the tunnel")
            return None, "yielded", pallas_failed
        batch, budget, kernel = rungs.pop(0)
        env, label = worker_rung_env(batch, kernel)
        res = _run_json(
            [sys.executable, "bench.py", "--worker"], budget, env,
        )
        if res.get("ok"):
            if kernel is None:
                # pallas works (again): restore the full-budget ladder,
                # and give the affine/lazy pallas rungs their chance
                # back too — a transient tunnel hang on an experiment
                # rung must not skip it for the rest of a multi-hour
                # watcher session once the flagship proves Mosaic
                # healthy (review r8)
                _mosaic_broken = False
                _affine_pallas_broken = False
                _lazy_pallas_broken = False
            _headline_banked = True
            _record("headline", {
                "metric": "sig_verify_throughput",
                "value": round(res["rate"], 1), "unit": "sigs/sec/chip",
                "device": res.get("device"), "kernel": res.get("kernel"),
                "batch": res.get("batch"), "step_ms": res.get("step_ms"),
                "compile_s": res.get("compile_s"),
                "init_s": res.get("init_s"),
                "profile_path": res.get("profile_path"),
            })
            return res, "banked", pallas_failed
        err = str(res.get("error", ""))
        _log(f"headline {label}: {err or '?'}")
        if kernel is None:
            pallas_failed = True
        if res.get("fatal"):
            # Correctness failure, not an infra flake: record it (which
            # poisons bench.py's watcher fallback for the round) and stop
            # sampling — a later flaky pass must never mask a mismatch.
            _record("fatal", {"error": res.get("error")})
            raise FatalMismatch(res.get("error", "verdict mismatch"))
        if "initializing backend" in err or "probing backend" in err:
            # jax.devices() blocked for the rung's whole budget: the
            # tunnel closed under us (live init is 0.1-5.8 s when up).
            # Abort the sweep — burning the remaining rungs against a
            # dead tunnel delays the next probe by up to 16 min
            # (observed r5, 03:54-04:16Z).
            _log("tunnel lost mid-sweep — back to probing")
            return None, "tunnel-lost", pallas_failed
        if kernel is None and (
            "MosaicError" in err or "timed out" in err
        ):
            # The compile helper is rejecting pallas programs outright
            # (observed r5: HTTP 500 on every pallas compile) or hanging
            # on them (observed r5 03:48Z: backend up in 0.2 s, then the
            # 32768 compile sat for 360 s) while plain XLA works.  Any
            # pallas timeout PAST backend init (the branch above caught
            # the init stage) is a post-init hang — at host prep, the
            # compile RPC, or the oracle check — and retrying a smaller
            # pallas compile in the same window is the losing bet; skip
            # to the XLA rungs this sweep and lead with XLA next sweep
            # (pallas retried at the tail).
            _log("mosaic compile broken/hanging — skipping to XLA rungs")
            _mosaic_broken = True
            rungs = [r for r in rungs if r[2] == "xla"]
    return None, "exhausted", pallas_failed


def run_affine() -> bool:
    """One pass over the affine point-form rungs (ISSUE 8): bank a
    device number for the new formulation as a ``kind="affine"`` row.
    Returns True when a sample was banked (the once-per-round slot is
    then spent).  Same short-window discipline as the headline sweep:
    yield to bench.py, abort on tunnel loss, fast-skip the pallas rung
    during a Mosaic outage, and treat a fatal verdict mismatch exactly
    like the headline's (recorded — poisoning the round — and raised).

    A failing AFFINE pallas rung sets only the affine-local broken flag
    (review r8): the affine program carries primitives Mosaic may reject
    while the projective flagship lowers fine (exactly what the
    mosaic_diag mixed_add/batch_inv cases probe), so conflating it with
    ``_mosaic_broken`` would degrade the PROJECTIVE headline ladder for
    the rest of the round over an experiment's failure."""
    global _affine_pallas_broken
    rungs = (
        [r for r in AFFINE_LADDER if r[2] == "xla"]
        if (_mosaic_broken or _affine_pallas_broken)
        else list(AFFINE_LADDER)
    )
    for batch, budget, kernel in rungs:
        if _bench_running():
            _log("affine: bench.py running — yielding the tunnel")
            return False
        env, label = worker_rung_env(batch, kernel, point_form="affine")
        res = _run_json(
            [sys.executable, "bench.py", "--worker"], budget, env,
        )
        if res.get("ok"):
            _record("affine", {
                "metric": "sig_verify_throughput",
                "value": round(res["rate"], 1), "unit": "sigs/sec/chip",
                "device": res.get("device"), "kernel": res.get("kernel"),
                "point_form": res.get("point_form", "affine"),
                "batch": res.get("batch"), "step_ms": res.get("step_ms"),
                "compile_s": res.get("compile_s"),
                "init_s": res.get("init_s"),
                "profile_path": res.get("profile_path"),
            })
            return True
        err = str(res.get("error", ""))
        _log(f"affine {label}: {err or '?'}")
        if res.get("fatal"):
            # an affine/oracle verdict mismatch is a kernel correctness
            # failure like any other: poison the round's sampling
            _record("fatal", {"error": res.get("error"),
                              "point_form": "affine"})
            raise FatalMismatch(res.get("error", "verdict mismatch"))
        if "initializing backend" in err or "probing backend" in err:
            _log("affine: tunnel lost — back to probing")
            return False
        if kernel is None and ("MosaicError" in err or "timed out" in err):
            _log("affine: pallas AFFINE program broken/hanging — affine "
                 "XLA rung only (projective headline ladder unaffected)")
            _affine_pallas_broken = True
    return False


def run_lazy() -> bool:
    """One pass over the lazy-reduction rungs (ISSUE 12): bank a device
    number for the lazy field pipeline (and the 5-bit windows on the
    leading rung) as a ``kind="lazy"`` row.  Returns True when a sample
    was banked (the once-per-round slot is then spent).  Same
    short-window discipline and failure isolation as :func:`run_affine`:
    a failing LAZY pallas rung sets only the lazy-local broken flag —
    the projective/eager headline ladder is never degraded by an
    experiment's failure — and a fatal verdict mismatch poisons the
    round exactly like the headline's."""
    global _lazy_pallas_broken
    rungs = (
        [r for r in LAZY_LADDER if r[2] == "xla"]
        if (_mosaic_broken or _lazy_pallas_broken)
        else list(LAZY_LADDER)
    )
    for batch, budget, kernel, reduce, wbits in rungs:
        if _bench_running():
            _log("lazy: bench.py running — yielding the tunnel")
            return False
        env, label = worker_rung_env(
            batch, kernel, field_reduce=reduce, window_bits=wbits
        )
        res = _run_json(
            [sys.executable, "bench.py", "--worker"], budget, env,
        )
        if res.get("ok"):
            _record("lazy", {
                "metric": "sig_verify_throughput",
                "value": round(res["rate"], 1), "unit": "sigs/sec/chip",
                "device": res.get("device"), "kernel": res.get("kernel"),
                "field_reduce": res.get("field_reduce", reduce),
                "window_bits": res.get("window_bits", wbits),
                "batch": res.get("batch"), "step_ms": res.get("step_ms"),
                "compile_s": res.get("compile_s"),
                "init_s": res.get("init_s"),
                "profile_path": res.get("profile_path"),
            })
            return True
        err = str(res.get("error", ""))
        _log(f"lazy {label}: {err or '?'}")
        if res.get("fatal"):
            # a lazy/oracle verdict mismatch is a kernel correctness
            # failure like any other: poison the round's sampling
            _record("fatal", {"error": res.get("error"),
                              "field_reduce": reduce,
                              "window_bits": wbits})
            raise FatalMismatch(res.get("error", "verdict mismatch"))
        if "initializing backend" in err or "probing backend" in err:
            _log("lazy: tunnel lost — back to probing")
            return False
        if kernel is None and ("MosaicError" in err or "timed out" in err):
            _log("lazy: pallas LAZY program broken/hanging — lazy XLA "
                 "rung only (projective headline ladder unaffected)")
            _lazy_pallas_broken = True
    return False


def run_mesh() -> bool:
    """One pass over the pod-mesh rungs (ISSUE 13): bank device numbers
    for 8/4/2-way sharded dispatch (bench.py --mesh-device) as
    ``kind="mesh"`` rows.  Returns True when at least one way was banked
    (the once-per-round slot is then spent).  Same short-window
    discipline and failure isolation as :func:`run_affine`: yield to
    bench.py, abort on tunnel loss, fall back to the XLA program inside
    shard_map when the MESH pallas program is broken/hanging (the
    projective headline ladder is never degraded by it), and a fatal
    mesh/oracle verdict mismatch poisons the round like the headline's."""
    global _mesh_pallas_broken
    banked = False
    for ways, budget, kernel in MESH_LADDER:
        while True:  # at most two attempts per way: pallas, then xla
            if _mosaic_broken or _mesh_pallas_broken:
                kernel = "xla"
            if _bench_running():
                _log("mesh: bench.py running — yielding the tunnel")
                return banked
            env = {
                "TPUNODE_BENCH_MESH_WAYS": str(ways),
                "TPUNODE_BENCH_BATCH": "4096",
                "TPUNODE_BENCH_REQUIRE_TPU": "1",
            }
            if kernel:
                env["TPUNODE_BENCH_KERNEL"] = kernel
            label = f"mesh{ways}x{'-' + kernel if kernel else ''}@4096"
            res = _run_json(
                [sys.executable, "bench.py", "--mesh-device"], budget, env,
            )
            if res.get("ok"):
                _record("mesh", {
                    "metric": "sig_verify_throughput",
                    "value": round(res["rate"], 1),
                    "unit": "sigs/sec_total",
                    "device": res.get("device"), "kernel": res.get("kernel"),
                    "mesh_ways": res.get("mesh_ways"),
                    "batch": res.get("batch"), "step_ms": res.get("step_ms"),
                    "compile_s": res.get("compile_s"),
                    "init_s": res.get("init_s"),
                    "profile_path": res.get("profile_path"),
                })
                banked = True
                break
            err = str(res.get("error", ""))
            _log(f"mesh {label}: {err or '?'}")
            if res.get("fatal"):
                # a mesh/oracle verdict mismatch is a kernel correctness
                # failure like any other: poison the round's sampling
                _record("fatal", {"error": res.get("error"),
                                  "mesh_ways": ways})
                raise FatalMismatch(res.get("error", "verdict mismatch"))
            if "initializing backend" in err or "probing backend" in err:
                _log("mesh: tunnel lost — back to probing")
                return banked
            if kernel is None and ("MosaicError" in err or "timed out" in err):
                # retry THIS way on the XLA program before moving on
                # (review r13: skipping it would silently drop the
                # 8-way headline sample for the whole round — the other
                # experiment ladders carry an explicit xla rung for
                # exactly this case)
                _log("mesh: pallas-inside-shard_map broken/hanging — "
                     f"retrying {ways}-way on the XLA program "
                     "(projective headline ladder unaffected)")
                _mesh_pallas_broken = True
                continue
            break
    return banked


def run_observability() -> bool:
    """Once-per-round observability-overhead sample (ISSUE 16/17): the
    bench.py --observability worker's sampler/SLO tick costs and burn-
    detection latency, passed through as a ``kind="observability"`` row.
    The worker never imports jax (JAX_PLATFORMS=cpu keeps the TPU shim
    honest), so unlike the tunnel-client slots this one runs even when
    the device is down and never needs to yield to bench.py.  A failed
    worker keeps the slot for a later window."""
    res = _run_json(
        [sys.executable, "bench.py", "--observability"],
        OBS_BUDGET, {"JAX_PLATFORMS": "cpu"},
    )
    if res.get("ok"):
        _record("observability", res)
        return True
    _log(f"observability: {res.get('error', '?')}")
    return False


def run_mesh_e2e() -> bool:
    """Once-per-round host-affine feed A/B sample (ISSUE 19): the
    bench.py --mesh-e2e worker's affine-vs-central e2e throughput at
    4-way under a slow host, per-host feed-idle fractions, and the
    campaign pass through the affine path, banked as a
    ``kind="mesh_e2e"`` row.  The worker is the cpu-native proxy
    (JAX_PLATFORMS=cpu, jax never imported), so like the observability
    slot it runs even when the device is down and never needs to yield
    to bench.py.  A failed worker keeps the slot for a later window; a
    campaign mismatch is fatal for the round (verdict divergence must
    never be masked by a later passing sample)."""
    res = _run_json(
        [sys.executable, "bench.py", "--mesh-e2e"],
        MESH_E2E_BUDGET, {"JAX_PLATFORMS": "cpu"},
    )
    if res.get("fatal"):
        _record("fatal", res)
        raise FatalMismatch(res.get("error", "affine verdict mismatch"))
    if res.get("ok"):
        _record("mesh_e2e", res)
        return True
    _log(f"mesh_e2e: {res.get('error', '?')}")
    return False


def run_serve() -> bool:
    """Once-per-round multi-tenant serve sample (ISSUE 20): the bench.py
    --serve worker's firehose — per-class verdict latency, cache
    hit-rate, the conservation pin, the induced-burn shed leg, and the
    receipt audit — banked as a ``kind="serve"`` row.  The worker is the
    cpu-native proxy (JAX_PLATFORMS=cpu, jax never imported), so like
    the mesh_e2e slot it runs even when the device is down and never
    needs to yield to bench.py.  A failed worker keeps the slot for a
    later window; a verdict divergence or conservation break is fatal
    for the round (never masked by a later passing sample)."""
    res = _run_json(
        [sys.executable, "bench.py", "--serve"],
        SERVE_BUDGET, {"JAX_PLATFORMS": "cpu"},
    )
    if res.get("fatal"):
        _record("fatal", res)
        raise FatalMismatch(res.get("error", "serve verdict mismatch"))
    if res.get("ok"):
        _record("serve", res)
        return True
    _log(f"serve: {res.get('error', '?')}")
    return False


def run_config(name: str) -> dict | None:
    if _bench_running():
        _log(f"{name}: bench.py running — yielding the tunnel")
        return None
    # During a Mosaic outage the config subprocess must start on the XLA
    # program: its fresh engine would otherwise pick pallas and — in the
    # outage's hang mode — sit in the compile until the watchdog kills
    # the whole config (TPUNODE_VERIFY_KERNEL seeds kernel.py's broken
    # flag).  A modest steady-state shape keeps the XLA server-side
    # compile inside the watchdog too — XLA throughput plateaus by 8192
    # (PERF.md r3 table), so nothing is lost.
    env = (
        {"TPUNODE_DEVICE_BATCH": "8192", "TPUNODE_VERIFY_KERNEL": "xla"}
        if _mosaic_broken else None
    )
    res = _run_json([sys.executable, "-m", "benchmarks.run", name],
                    CONFIG_BUDGETS[name], env)
    if res.get("metric"):
        _record(name, res)
        return res
    _log(f"{name}: {res.get('error', '?')}")
    return None


FATAL_WINDOW_S = 12 * 3600  # matches bench.py's DEVICE_RUN_MAX_AGE

PID_PATH = os.path.join(REPO, "benchmarks", ".watcher_pid")


def _another_watcher_alive() -> bool:
    """Is a DIFFERENT live watcher process already registered in
    ``.watcher_pid``?  Two watchers would contend for the tunnel (probes
    block each other) and double-sample; a relaunch race nearly created
    this (observed r5, 04:38Z).  Best-effort: any read/parse failure
    means "no".  The cmdline match requires the interpreter AND the
    module form (``python -m benchmarks.watcher``) so a recycled pid on
    e.g. ``tail -F benchmarks/watcher_r5.log`` can't false-positive and
    block the round's sampler."""
    try:
        pid = int(open(PID_PATH, encoding="utf-8").read().split()[0])
    except (OSError, ValueError, IndexError):
        return False
    if pid == os.getpid():
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmd = f.read().decode("utf-8", "replace")
    except OSError:
        return False
    return "python" in cmd and "benchmarks.watcher" in cmd


def _claim_pidfile(retries: int = 6, wait_s: float = 5.0) -> bool:
    """Register this process as THE watcher; False means another live
    watcher kept the claim.

    The whole check-and-claim is serialized under an exclusive ``flock``
    on a sidecar lock file (ADVICE r5 #4): concurrent launchers decide
    stale-vs-live and write their pid one at a time, so the
    overwrite-then-recheck TOCTOU window — and the narrower
    read-stale/delete-fresh race a bare ``O_CREAT|O_EXCL`` scheme keeps
    (POSIX has no atomic compare-and-delete) — cannot occur.  The lock
    file itself is NEVER deleted: removing it would let a late claimer
    lock a fresh inode while an earlier one still holds the old, which
    reopens the double-watcher hole.  A claim whose registered process
    is dead (or recycled into a non-watcher) is simply overwritten under
    the lock.  A kill-and-relaunch race must not strand the round with
    no sampler: while a LIVE watcher holds the claim, wait briefly for
    it to finish dying before giving up."""
    import fcntl

    for i in range(retries):
        try:
            lock_fd = os.open(PID_PATH + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
        except OSError:
            return True  # unwritable pidfile dir: claim uncontested, proceed
        try:
            try:
                fcntl.flock(lock_fd, fcntl.LOCK_EX)  # held µs: check+write
            except OSError:
                pass  # flock-less fs (e.g. ENOLCK): unlocked best-effort,
                # but NEVER skip the liveness check below — claiming
                # blind would reopen the double-watcher hole
            if _another_watcher_alive():
                if i == retries - 1:
                    return False
            else:
                try:
                    with open(PID_PATH, "w", encoding="utf-8") as f:
                        f.write(f"{os.getpid()}\n")
                except OSError:
                    pass  # unwritable pidfile: claim uncontested, proceed
                return True
        finally:
            os.close(lock_fd)  # releases the flock
        time.sleep(wait_s)
    return False


def _release_pidfile() -> None:
    """Remove the pidfile iff it is still ours (a stale file would feed
    the pid-reuse scenario on the next round)."""
    try:
        if int(open(PID_PATH, encoding="utf-8").read().split()[0]) == os.getpid():
            os.remove(PID_PATH)
    except (OSError, ValueError, IndexError):
        pass


def _rotate_runs_file() -> list[dict]:
    """One rotation per round: a previous round's committed samples must
    never be reported as in-round (bench.py trusts this file).

    Recent ``fatal`` rows (device/oracle verdict mismatches) are carried
    FORWARD into the fresh file: a mid-round watcher relaunch must not
    launder a correctness failure behind a later flaky pass (review r5).
    Returns the carried rows so main() can refuse to sample.

    ``TPUNODE_WATCHER_KEEP_RUNS=1`` skips the rotation entirely — the
    flag for a MID-ROUND relaunch (watcher died, code updated), where
    rotating would discard genuinely in-round banked samples that
    bench.py should still report.  Fatal rows in the kept file still
    poison sampling (scanned and returned exactly as after a rotation).
    """
    if not os.path.exists(RUNS_PATH):
        return []
    keep = os.environ.get("TPUNODE_WATCHER_KEEP_RUNS", "") == "1"
    fatals: list[dict] = []
    kept_rows: list[str] = []   # in-window rows, verbatim
    parsed: list[dict] = []     # same rows, decoded (history folding)
    dropped = 0
    now = time.time()
    try:
        with open(RUNS_PATH, encoding="utf-8") as f:
            for line in f:
                try:
                    row = json.loads(line)
                    fresh = (
                        isinstance(row, dict)
                        and now - float(row.get("unix", 0)) < FATAL_WINDOW_S
                    )
                except (json.JSONDecodeError, TypeError, ValueError):
                    fresh = False
                if not fresh:
                    dropped += 1
                    continue
                kept_rows.append(line)
                parsed.append(row)
                if row.get("kind") == "fatal":
                    fatals.append(row)
    except OSError:
        pass
    if keep:
        # Fail closed against a leaked flag at a round-START launch:
        # even under keep, rows older than the in-round window are
        # rewritten away (same cap bench.py applies), so a previous
        # round's samples can never be reported as in-round.  Atomic
        # temp+replace: a kill mid-rewrite must not lose the banked
        # samples the keep flag exists to preserve.
        if dropped:
            try:
                tmp = RUNS_PATH + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.writelines(kept_rows)
                os.replace(tmp, RUNS_PATH)
            except OSError:
                pass
        _log(f"mid-round relaunch: keeping runs file "
             f"({len(kept_rows)} in-round row(s), {dropped} stale dropped"
             + (f", {len(fatals)} fatal row(s) still poison sampling)"
                if fatals else ")"))
        return fatals
    # The rotated-away round is over: fold its banked samples into the
    # cross-round history BEFORE they leave the runs file, so the next
    # round's fresh samples have a baseline to regress against.
    _fold_history(parsed)
    os.replace(RUNS_PATH, PREV_RUNS_PATH)
    _log(f"rotated stale {RUNS_PATH} -> {PREV_RUNS_PATH}")
    if fatals:
        with open(RUNS_PATH, "w", encoding="utf-8") as f:
            for row in fatals:
                f.write(json.dumps(row) + "\n")
        _log(f"carried {len(fatals)} recent fatal row(s) forward")
    return fatals


def handle_window(swept: set) -> float:
    """One live-window pass: headline sweep, same-window pallas upgrade,
    config sweep, once-per-round affine point-form sample (ISSUE 8),
    once-per-round lazy-reduction sample (ISSUE 12), once-per-round
    pod-mesh sharding sample (ISSUE 13), once-per-round
    Mosaic diagnostic, once-per-round device-free observability-overhead
    sample (ISSUE 16/17), once-per-round device-free host-affine feed
    A/B sample (ISSUE 19).  Mutates ``swept``
    (the on-device captures so far this round) and returns the sleep
    interval until the next probe.  Raises FatalMismatch to stop the
    watcher for the round.

    Order is load-bearing (review r5): the pallas upgrade runs BEFORE
    the configs — if pallas is hang-broken the upgrade detects it in one
    360 s rung and the configs then get the XLA knob; configs-first
    would feed config3's fresh engine a hanging pallas warmup and burn
    its whole 900 s budget.  The diagnostic (itself a tunnel client)
    only runs when the ladder proved the device live: never after a
    "yielded" sweep (it would contend with the bench we just yielded
    to) or a "tunnel-lost" one (480 s against a dead tunnel)."""
    head, why, pallas_failed = run_headline()
    if head is not None:
        if (
            head.get("kernel") == "xla"
            and not _mosaic_broken
            and not pallas_failed
        ):
            # FIRSTBANK banked the quick XLA number, pallas has not been
            # seen broken AND the banking sweep never reached (and
            # failed) the pallas rungs itself: chase the pallas headline
            # NOW — the ~6-9 min windows don't survive a 15 min refresh
            # wait.  When the sweep DID just fail those rungs (e.g. a
            # non-Mosaic worker crash, which doesn't set the broken
            # flag), re-running the identical rungs would burn up to
            # ~540 s of the window before the configs (ADVICE r5 #1).
            _log("same-window upgrade: pallas ladder attempt")
            up_head, up_why, _up_pf = run_headline(pallas_only=True)
            if up_head is not None:
                head = up_head
            elif up_why in ("yielded", "tunnel-lost"):
                # The window closed (or bench.py took the tunnel) during
                # the upgrade: no more tunnel clients — skip the configs
                # and go straight back to cheap probing.
                return PROBE_INTERVAL
        for name in CONFIG_ORDER:
            if name not in swept and run_config(name) is not None:
                swept.add(name)
        # Affine point-form sample (ISSUE 8): once per round, AFTER the
        # configs — the projective headline and the config money shots
        # outrank banking the new formulation's number, and a short
        # window must not spend itself on the experiment first.
        if "affine" not in swept and run_affine():
            swept.add("affine")
        # Lazy-reduction sample (ISSUE 12): once per round, after the
        # affine slot — same experiment-last discipline.
        if "lazy" not in swept and run_lazy():
            swept.add("lazy")
        # Pod-mesh sample (ISSUE 13): once per round, after the lazy
        # slot — 8/4/2-way sharded dispatch numbers (kind="mesh" rows)
        # so the first uptime window converts the pod bet too.
        if "mesh" not in swept and run_mesh():
            swept.add("mesh")
    if (
        (why == "exhausted" or (head is not None and _mosaic_broken))
        and "mosaic_diag" not in swept
    ):
        # The outage was seen, or the whole ladder failed on a live
        # device — either way this window must at least produce a
        # diagnosis (benchmarks/mosaic_diag.py; once per round).
        diag = _run_json(
            [sys.executable, "-m", "benchmarks.mosaic_diag"],
            480.0,
        )
        if diag.get("cases"):
            _record("mosaic_diag", diag)
            swept.add("mosaic_diag")
        else:
            # transient failure (e.g. tunnel died mid-diag): keep the
            # once-per-round slot for a later window
            _log(f"mosaic_diag: {diag.get('error', '?')}")
    # Observability-overhead sample (ISSUE 16/17): once per round,
    # device-free, so it runs even when the tunnel is down.
    if "observability" not in swept and run_observability():
        swept.add("observability")
    # Host-affine feed A/B sample (ISSUE 19): once per round, cpu-native
    # and device-free like the observability slot — banks the
    # affinity-on/off throughput row even when the tunnel is down.
    if "mesh_e2e" not in swept and run_mesh_e2e():
        swept.add("mesh_e2e")
    # Multi-tenant serve sample (ISSUE 20): once per round, cpu-native
    # and device-free like the slots above — banks the firehose/shed/
    # receipt-audit row even when the tunnel is down.
    if "serve" not in swept and run_serve():
        swept.add("serve")
    # Back off to the slow refresh cadence only once every config is
    # banked: with all of them captured the next window owes us nothing
    # but a headline refresh, but while configs are missing the next
    # short, rare window must be caught within one probe interval.
    return (
        REFRESH_INTERVAL
        if head is not None and swept.issuperset(CONFIG_ORDER)
        else PROBE_INTERVAL
    )


def main() -> None:
    start = time.time()
    deadline = start + DEADLINE_S
    if not _claim_pidfile():
        _log("another live watcher kept the claim in "
             f"{PID_PATH} — exiting (two watchers would contend "
             "for the tunnel)")
        return
    try:
        _main_claimed(deadline)
    finally:
        _release_pidfile()


def _main_claimed(deadline: float) -> None:
    if _rotate_runs_file():
        _log("recent FATAL verdict mismatch on record — refusing to "
             "sample until the kernel is fixed and the fatal rows are "
             "cleared deliberately")
        return
    swept: set[str] = set()   # configs captured on-device this round
    _log(f"watcher up (pid {os.getpid()}), deadline in "
         f"{DEADLINE_S/3600:.1f}h, probing every {PROBE_INTERVAL:.0f}s")
    n_probe = 0
    while time.time() < deadline:
        # The driver's round-end bench gets the tunnel to itself: clients
        # block each other, so probing while it runs could starve the
        # official artifact.  Stale locks (>30 min — a dead bench) are
        # ignored.
        if _bench_running():
            _log("bench.py running — pausing sampling")
            time.sleep(60)
            continue
        n_probe += 1
        tick = time.time()
        p = probe()
        if p.get("ok") and p.get("platform") == "tpu":
            _log(f"probe #{n_probe}: TPU UP "
                 f"({p.get('device_kind')}, init {p.get('init_s')}s)")
            try:
                interval = handle_window(swept)
            except FatalMismatch as e:
                _log(f"FATAL verdict mismatch — watcher stops sampling: {e}")
                return
        else:
            _log(f"probe #{n_probe}: down "
                 f"({p.get('error') or 'platform=' + str(p.get('platform'))})")
            interval = PROBE_INTERVAL
        # Interval measures probe-start to probe-start: a timed-out probe
        # (150s) must not ADD a full sleep on top, or the real gap doubles
        # and can eat most of a short uptime window.
        elapsed = time.time() - tick
        time.sleep(max(5.0, min(interval - elapsed, deadline - time.time())))
    _log(f"watcher deadline reached after {n_probe} probes; "
         f"configs captured on-device: {sorted(swept) or 'none'}")


if __name__ == "__main__":
    main()
