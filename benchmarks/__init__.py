"""Benchmark harness for the five BASELINE.json configurations.

Run: ``python -m benchmarks.run [config1|config2|config3|config4|config5|all]``

Each config prints one JSON line with the same schema as the driver's
bench.py ({"metric", "value", "unit", "vs_baseline", ...}) plus
config-specific detail fields.  The repo-root bench.py remains the
driver's single headline number (config 2's shape).
"""
