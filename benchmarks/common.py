"""Workload helpers shared by the driver headline bench (repo-root
bench.py) and the full config harness (benchmarks/run.py) — one generator,
so the two can't drift apart."""

from __future__ import annotations

import random
import time

__all__ = [
    "make_triples",
    "tile",
    "device_kind",
    "cpu_single_core_bench",
    "cpu_single_core_rate",
]


def make_triples(n: int, seed: int = 0xBE5C, invalid_every: int = 16):
    """Deterministic (pubkey, z, r, s) items; every ``invalid_every``-th has
    a corrupted message to keep verifiers honest."""
    from tpunode.verify.ecdsa_cpu import CURVE_N, GENERATOR, point_mul, sign

    rng = random.Random(seed)
    items = []
    for i in range(n):
        priv = rng.getrandbits(256) % CURVE_N or 1
        pub = point_mul(priv, GENERATOR)
        z = rng.getrandbits(256)
        r, s = sign(priv, z, rng.getrandbits(256) % CURVE_N or 1)
        if invalid_every and i % invalid_every == invalid_every - 1:
            z ^= 1
        items.append((pub, z, r, s))
    return items


def tile(items, n):
    """Repeat a unique pool out to ``n`` items (device work is identical)."""
    return (items * (n // len(items) + 1))[:n]


def device_kind() -> str:
    import jax

    d = jax.devices()[0]
    return f"{d.platform}:{getattr(d, 'device_kind', '?')}"


def cpu_single_core_bench(sample) -> tuple[float, str, list]:
    """Single-core CPU baseline: returns (sigs/sec, engine_name, verdicts).

    Engine load (which may compile the C++ extension on first use) and the
    warm-up batch happen OUTSIDE the timed window.  ``engine_name`` is
    "native-cpp" or "python-oracle" so emitted baselines say which engine
    defined them (the oracle is orders of magnitude slower — a silent
    fallback would corrupt every downstream speedup ratio)."""
    from tpunode.verify.cpu_native import load_native_verifier

    fn = None
    engine = "python-oracle"
    try:
        v = load_native_verifier()
        if v is not None:
            fn = v.verify_batch
            engine = "native-cpp"
    except Exception:
        pass
    if fn is None:
        from tpunode.verify.ecdsa_cpu import verify_batch_cpu as fn
    fn(sample[:8])  # warm (outside the timed window)
    t0 = time.perf_counter()
    out = fn(sample)
    rate = len(sample) / (time.perf_counter() - t0)
    return rate, engine, out


def cpu_single_core_rate(sample) -> float:
    """Back-compat shim: just the rate."""
    return cpu_single_core_bench(sample)[0]
