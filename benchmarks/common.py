"""Workload helpers shared by the driver headline bench (repo-root
bench.py) and the full config harness (benchmarks/run.py) — one generator,
so the two can't drift apart."""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import time

__all__ = [
    "make_triples",
    "tile",
    "device_kind",
    "cpu_single_core_bench",
    "cpu_single_core_stats",
    "cpu_single_core_rate",
    "run_json_subprocess",
]


def run_json_subprocess(
    argv: list, timeout: float, env_extra: dict | None = None,
    cwd: str | None = None,
) -> dict:
    """Run a subprocess in its own process group; parse its last JSON line.

    Shared by bench.py's watchdog ladder and benchmarks/watcher.py (the
    round-long sampler) so the trickiest subprocess logic exists once:
    the whole process GROUP is killed on timeout, because the TPU shim
    spawns helpers that inherit the stdout pipe and killing only the
    direct child leaves communicate() blocked on them forever.  On
    timeout, the worker's last ``[bench-worker]`` stderr progress line is
    surfaced so the error says what the worker was doing.
    """
    env = dict(os.environ)
    env.update(env_extra or {})
    proc = subprocess.Popen(
        argv, cwd=cwd, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            _, stderr = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            stderr = ""
        last = ""
        for line in (stderr or "").splitlines():
            if line.startswith("[bench-worker]"):
                last = line
        return {
            "ok": False,
            "error": f"timed out after {timeout:.0f}s"
            + (f" (last: {last})" if last else ""),
        }
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {
        "ok": False,
        "error": f"worker rc={proc.returncode}, no JSON "
        f"(stderr tail: {(stderr or '')[-300:]!r})",
    }


def worker_rung_env(batch: int, kernel: str | None = None,
                    point_form: str | None = None,
                    field_reduce: str | None = None,
                    window_bits: int | None = None):
    """Env + display label for one device-ladder rung.

    Shared by bench.py's round-end ladder and benchmarks/watcher.py (the
    round-long sampler) so the TPUNODE_BENCH_* worker contract lives in
    one place: ``kernel`` None means auto-select (pallas on TPU), "xla"
    forces the portable XLA program (the Mosaic-outage fallback);
    ``point_form`` selects the MSM point form (ISSUE 8 — the watcher's
    affine rungs ride this); ``field_reduce``/``window_bits`` select the
    ISSUE 12 lazy-reduction / window-width formulation (the watcher's
    ``kind="lazy"`` rungs).  None keeps the worker's process default.
    """
    env = {"TPUNODE_BENCH_BATCH": str(batch),
           "TPUNODE_BENCH_REQUIRE_TPU": "1"}
    label = f"tpu{'-' + kernel if kernel else ''}@{batch}"
    if kernel:
        env["TPUNODE_BENCH_KERNEL"] = kernel
    if point_form:
        env["TPUNODE_POINT_FORM"] = point_form
        label += f"/{point_form}"
    if field_reduce:
        env["TPUNODE_FIELD_REDUCE"] = field_reduce
        label += f"/{field_reduce}"
    if window_bits:
        env["TPUNODE_WINDOW_BITS"] = str(window_bits)
        label += f"/w{window_bits}"
    return env, label


def make_triples(n: int, seed: int = 0xBE5C, invalid_every: int = 16):
    """Deterministic (pubkey, z, r, s) items; every ``invalid_every``-th has
    a corrupted message to keep verifiers honest."""
    from tpunode.verify.ecdsa_cpu import CURVE_N, GENERATOR, point_mul, sign

    rng = random.Random(seed)
    items = []
    for i in range(n):
        priv = rng.getrandbits(256) % CURVE_N or 1
        pub = point_mul(priv, GENERATOR)
        z = rng.getrandbits(256)
        r, s = sign(priv, z, rng.getrandbits(256) % CURVE_N or 1)
        if invalid_every and i % invalid_every == invalid_every - 1:
            z ^= 1
        items.append((pub, z, r, s))
    return items


def tile(items, n):
    """Repeat a unique pool out to ``n`` items (device work is identical)."""
    return (items * (n // len(items) + 1))[:n]


def device_kind() -> str:
    import jax

    d = jax.devices()[0]
    return f"{d.platform}:{getattr(d, 'device_kind', '?')}"


def cpu_single_core_bench(sample, runs: int = 5) -> tuple[float, str, list]:
    """Single-core CPU baseline: returns (sigs/sec, engine_name, verdicts).

    The rate is the MEDIAN of ``runs`` timed passes (VERDICT r5 weak #7:
    a single pass on a busy 1-core box drifted ``vs_baseline`` ±25%
    round-over-round; the median of 5 is stable against transient load).
    Use :func:`cpu_single_core_stats` for the per-run spread.

    Engine load (which may compile the C++ extension on first use) and the
    warm-up batch happen OUTSIDE the timed window.  ``engine_name`` is
    "native-cpp" or "python-oracle" so emitted baselines say which engine
    defined them (the oracle is orders of magnitude slower — a silent
    fallback would corrupt every downstream speedup ratio)."""
    stats = cpu_single_core_stats(sample, runs=runs)
    return stats["rate"], stats["engine"], stats["verdicts"]


def cpu_single_core_stats(sample, runs: int = 5) -> dict:
    """:func:`cpu_single_core_bench` with the spread: ``{rate`` (median),
    ``rate_min``, ``rate_max``, ``rate_spread`` (max/min - 1), ``runs``,
    ``engine``, ``verdicts}`` — the artifact records the spread so a
    drifting ``vs_baseline`` is attributable to host load, not guessed."""
    import statistics

    from tpunode.verify.cpu_native import load_native_verifier

    fn = None
    engine = "python-oracle"
    try:
        v = load_native_verifier()
        if v is not None:
            fn = v.verify_batch
            engine = "native-cpp"
    except Exception:
        pass
    if fn is None:
        from tpunode.verify.ecdsa_cpu import verify_batch_cpu as fn

        # the pure-Python oracle is ~3 orders slower: one timed pass is
        # already tens of seconds on this box, N more would blow budgets
        runs = 1
    fn(sample[:8])  # warm (outside the timed window)
    rates = []
    out: list = []
    for _ in range(max(1, runs)):
        t0 = time.perf_counter()
        out = fn(sample)
        rates.append(len(sample) / (time.perf_counter() - t0))
    return {
        "rate": statistics.median(rates),
        "rate_min": min(rates),
        "rate_max": max(rates),
        "rate_spread": max(rates) / min(rates) - 1.0,
        "runs": len(rates),
        "engine": engine,
        "verdicts": out,
    }


def cpu_single_core_rate(sample) -> float:
    """Back-compat shim: just the rate."""
    return cpu_single_core_bench(sample)[0]
