"""Kernel-vs-oracle adversarial campaign (reproducible harness).

Validates the XLA device program (``verify_device`` on cpu-jax — the
same program the TPU runs) against the C++ batch verifier (itself
pinned to the pure-Python consensus oracle in tests) on randomized
valid signatures plus adversarial shapes for all three algorithms:

* message bit-flips (z ^ 1) and signature bit-flips (s ^ 1);
* ``r = x + n`` aliasing (ECDSA accepts via the x+n branch — valid!);
* ``s -> n - s`` ECDSA twins (valid: low-s normalization ambiguity);
* boundary values ``r = p - 1``, ``s = n - 1``, ``r = 0``, ``s = 0``;
* absent / infinity / off-curve pubkeys;
* non-canonicalized-nonce Schnorr/BIP340 twins — x(R) matches, only
  jacobi/parity rejects (the shapes that pin the r5 gated acceptance
  pows at scale).

Run (CPU-only, never touches the tunnel):

    JAX_PLATFORMS=cpu python -m benchmarks.campaign [unique_pool] [batch]
    JAX_PLATFORMS=cpu python -m benchmarks.campaign --pallas [pool] [batch]

``--pallas`` sends the same pool through the flagship Pallas program in
interpret mode (numpy semantics of the exact Mosaic program; block 32)
instead of the XLA program — both device paths validated by one
harness.  ``--field-mul=shift_add|dot_general`` and
``--field-sqr=half|mul`` select the limb-product formulation (ISSUE 4);
``--point-form projective|affine`` selects the MSM point form (ISSUE 8):
a new formulation must produce ZERO mismatches on the full adversarial
pool before it is eligible for dispatch.  Prints one JSON line: items
compared, mismatches (MUST be 0), the formulation, and the per-shape
tally.
Replaces the one-off scripts behind PERF.md's r5 campaign notes with a
committed, re-runnable harness.
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_pool(n_base: int, rng: random.Random):
    """(items, shapes): adversarial pool of unique verify items, tagged
    with the shape that produced each (for the tally)."""
    from tpunode.verify.ecdsa_cpu import (
        CURVE_N,
        CURVE_P,
        GENERATOR,
        Point,
        bip340_challenge,
        jacobi,
        lift_x,
        point_mul,
        schnorr_challenge,
        sign,
        sign_bip340,
        sign_schnorr,
    )

    items, shapes, expects = [], [], []

    def add(item, shape, expect_valid):
        """``expect_valid`` is the shape's REQUIRED verdict: asserting it
        (not just device == oracle) catches a regression that weakens
        both lanes identically (e.g. shared host prep dropping the
        schnorr/bip340 flags so twins verify as plain ECDSA everywhere)."""
        items.append(item)
        shapes.append(shape)
        expects.append(expect_valid)

    def nonce_with(pred):
        while True:
            k = rng.getrandbits(256) % CURVE_N or 1
            R = point_mul(k, GENERATOR)
            if pred(R):
                return k, R

    for i in range(n_base):
        priv = rng.getrandbits(256) % CURVE_N or 1
        pub = point_mul(priv, GENERATOR)
        z = rng.getrandbits(256)
        algo = i % 3
        if algo == 0:  # ECDSA + mutations
            r, s = sign(priv, z, rng.getrandbits(256) % CURVE_N or 1)
            add((pub, z, r, s), "ecdsa-valid", True)
            add((pub, z ^ 1, r, s), "ecdsa-zflip", False)
            add((pub, z, r, s ^ 1), "ecdsa-sflip", False)
            add((pub, z, r, CURVE_N - s), "ecdsa-neg-s", True)  # valid twin
            if r + CURVE_N < CURVE_P:
                # requires x(R) < p - n (~2^-129 for random R): never
                # fires randomly; the m2/r2_valid branch is pinned by
                # synthetic unit tests instead
                add((pub, z, r + CURVE_N, s), "ecdsa-r-alias", True)
            add((pub, z, CURVE_P - 1, s), "ecdsa-r-boundary", False)
            add((pub, z, r, CURVE_N - 1), "ecdsa-s-boundary", False)
            add((pub, z, 0, s), "ecdsa-r0", False)
            add((pub, z, r, 0), "ecdsa-s0", False)
            add((None, z, r, s), "ecdsa-no-pub", False)
            add((Point(None, None), z, r, s), "ecdsa-inf-pub", False)
            add((Point(5, 7), z, r, s), "ecdsa-off-curve", False)
        elif algo == 1:  # BCH Schnorr + mutations
            r, s = sign_schnorr(priv, z, rng.getrandbits(256))
            e = schnorr_challenge(r, pub, z)
            add((pub, e, r, s, "schnorr"), "schnorr-valid", True)
            add((pub, e ^ 1, r, s, "schnorr"), "schnorr-eflip", False)
            add((pub, e, r, s ^ 1, "schnorr"), "schnorr-sflip", False)
            add((pub, e, r, CURVE_N - s, "schnorr"), "schnorr-neg-s", False)
            k, R = nonce_with(lambda R: jacobi(R.y) != 1)
            e2 = schnorr_challenge(R.x, pub, z)
            add((pub, e2, R.x, (k + e2 * priv) % CURVE_N, "schnorr"),
                "schnorr-jacobi-twin", False)
        else:  # BIP340 + mutations
            P0 = pub  # same point; the scalar mult is the pool's hot op
            d = priv if P0.y % 2 == 0 else CURVE_N - priv
            r, s = sign_bip340(priv, z, rng.getrandbits(256))
            e = bip340_challenge(r, P0.x, z)
            pub340 = lift_x(P0.x)
            add((pub340, e, r, s, "bip340"), "bip340-valid", True)
            add((pub340, e ^ 1, r, s, "bip340"), "bip340-eflip", False)
            add((pub340, e, r, s ^ 1, "bip340"), "bip340-sflip", False)
            add((pub340, e, r, CURVE_N - s, "bip340"), "bip340-neg-s", False)
            k, R = nonce_with(lambda R: R.y % 2 != 0)
            e2 = bip340_challenge(R.x, P0.x, z)
            add((pub340, e2, R.x, (k + e2 * d) % CURVE_N, "bip340"),
                "bip340-parity-twin", False)
    return items, shapes, expects


def run_campaign(
    n_base: int,
    batch: int,
    pallas: bool = False,
    field_mul: str | None = None,
    field_sqr: str | None = None,
    point_form: str | None = None,
    field_reduce: str | None = None,
    window_bits: int | None = None,
) -> dict:
    """Build the pool and compare the chosen device program against the
    C++ verifier AND each shape's required verdict.  Returns the result
    dict (``mismatches`` MUST be 0).  ``field_mul``/``field_sqr`` select
    the limb-product formulation, ``point_form`` the MSM point form
    (ISSUE 8), ``field_reduce`` the reduction discipline and
    ``window_bits`` the MSM window width (ISSUE 12) process-wide (None
    keeps the active mode); every dispatch path retraces per mode."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from tpunode.verify import curve as C
    from tpunode.verify import field as F
    from tpunode.verify import kernel as K
    from tpunode.verify.cpu_native import load_native_verifier
    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.engine import enable_compile_cache
    from tpunode.verify.kernel import verify_batch_tpu

    enable_compile_cache()
    if field_mul is not None or field_sqr is not None or field_reduce is not None:
        F.set_field_modes(mul=field_mul, sqr=field_sqr, reduce=field_reduce)
    if point_form is not None:
        C.set_point_form(point_form)
    if window_bits is not None:
        K.set_kernel_modes(window_bits=window_bits)
    if pallas:
        import jax.numpy as jnp

        from tpunode.verify.kernel import collect_verdicts, prepare_batch
        from tpunode.verify.pallas_kernel import verify_blocked

        def device_verify(chunk, pad_to):
            prep = prepare_batch(chunk, pad_to=pad_to)
            out = verify_blocked(
                *(jnp.asarray(a) for a in prep.device_args),
                interpret=True, block=32,
            )
            return collect_verdicts(out, len(chunk))
    else:
        def device_verify(chunk, pad_to):
            return verify_batch_tpu(chunk, pad_to=pad_to)

    rng = random.Random(0xCA4)
    t0 = time.time()
    items, shapes, expects = build_pool(n_base, rng)
    gen_s = time.time() - t0

    native = load_native_verifier()
    oracle = (
        (lambda xs: native.verify_batch(xs))
        if native is not None else verify_batch_cpu
    )

    t0 = time.time()
    mismatches = []
    tally: dict[str, list[int]] = {}
    for lo in range(0, len(items), batch):
        chunk = items[lo:lo + batch]
        got = device_verify(chunk, batch)
        expect = oracle(chunk)
        for j, (g, e) in enumerate(zip(got, expect)):
            shape = shapes[lo + j]
            ok_n, n = tally.get(shape, [0, 0])
            tally[shape] = [ok_n + (1 if g else 0), n + 1]
            if g != e or g != expects[lo + j]:
                mismatches.append(
                    {"index": lo + j, "shape": shape, "device": g,
                     "oracle": e, "required": expects[lo + j]}
                )
    run_s = time.time() - t0
    return {
        "items": len(items),
        "mismatches": len(mismatches),
        "mismatch_detail": mismatches[:10],
        "kernel": "pallas-interpret" if pallas else "xla",
        "field_modes": {
            "mul": F.mul_mode(),
            "sqr": F.sqr_mode(),
            "reduce": F.reduce_mode(),
        },
        "point_form": C.point_form(),
        "window_bits": K.window_bits(),
        "gen_s": round(gen_s, 1),
        "run_s": round(run_s, 1),
        "oracle": "native-cpp" if native is not None else "python",
        "tally": {k: {"accepted": v[0], "total": v[1]}
                  for k, v in sorted(tally.items())},
    }


def main() -> None:
    pallas = "--pallas" in sys.argv
    field_mul = field_sqr = point_form = field_reduce = None
    window_bits = None
    pos = []
    args = list(sys.argv[1:])
    while args:
        a = args.pop(0)
        if a == "--pallas":
            continue
        if a.startswith("--field-mul="):
            field_mul = a.split("=", 1)[1]
        elif a.startswith("--field-sqr="):
            field_sqr = a.split("=", 1)[1]
        elif a.startswith("--point-form="):
            point_form = a.split("=", 1)[1]
        elif a == "--point-form":  # ISSUE 8 spells it space-separated
            if not args:
                sys.exit("--point-form needs a value (projective|affine)")
            point_form = args.pop(0)
        elif a.startswith("--field-reduce="):
            field_reduce = a.split("=", 1)[1]
        elif a == "--field-reduce":  # ISSUE 12 spells it space-separated
            if not args:
                sys.exit("--field-reduce needs a value (eager|lazy)")
            field_reduce = args.pop(0)
        elif a.startswith("--window-bits="):
            window_bits = int(a.split("=", 1)[1])
        elif a == "--window-bits":
            if not args:
                sys.exit("--window-bits needs a value (4|5)")
            window_bits = int(args.pop(0))
        else:
            pos.append(a)
    n_base = int(pos[0]) if pos else (32 if pallas else 256)
    batch = int(pos[1]) if len(pos) > 1 else (256 if pallas else 2048)
    if pallas and batch % 32:
        sys.exit(f"--pallas batch must be a multiple of the 32-lane "
                 f"interpret block (got {batch})")
    res = run_campaign(n_base, batch, pallas=pallas,
                       field_mul=field_mul, field_sqr=field_sqr,
                       point_form=point_form, field_reduce=field_reduce,
                       window_bits=window_bits)
    print(json.dumps(res))
    if res["mismatches"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
