"""Bounded Mosaic-outage diagnostic (r5).

The axon remote-compile helper is 500ing on every Pallas program this
round (``MosaicError: .../remote_compile: HTTP 500``) while plain XLA
programs compile and run on the same device.  This script discriminates
the two possible causes when an uptime window allows:

1. ``trivial``  — a 2-line Pallas add kernel.  If THIS fails, the compile
   helper is broken for all Mosaic programs (infra outage; nothing to fix
   in-repo).
2. ``field_mul`` — one pallas_field.mul over a (24, 256) block, the verify
   kernel's core op.  Separates "our field formulas" from "any kernel".
3. ``table_build`` — VMEM scratch table via pl.ds dynamic stores in a
   fori_loop (the kernel's r3-era Q-table pattern).
4. ``pow_window`` — the r4 windowed pow with dynamic scalar digit loads
   from a (2, 64) VMEM ref (the original suspect construct).
5. ``pow_window_smem`` — the same pow with the digits in SMEM, the
   canonical placement the kernel now uses (pallas_kernel.py:190-215).
   ``pow_window`` failing while this passes confirms the VMEM read as
   the cause and the SMEM fix as sufficient.
6. ``mixed_add`` / ``batch_inv`` / ``pow_descan`` / ``select_tree`` —
   the ISSUE-8 affine-MSM primitives (complete mixed addition, the
   Montgomery-trick batch inversion with its SMEM-digit Fermat ladder,
   the de-scanned static-digit pow, the 4-level select tree), each as a
   minimal kernel so a short uptime window can bisect which ones Mosaic
   lowers before the affine flagship is attempted on device.
   ``lazy_reduce`` / ``window5`` (ISSUE 12) extend the set with the
   lazy pipeline's wide accumulator (47-sublane intermediates, one
   loose reduction per expression) and the 5-bit window constructs
   (32-entry VMEM table, 5-level select tree, ONE shared G-table copy
   broadcast across lanes).
7. ``flagship`` — the real ``verify_blocked`` at batch 256 (one block).
   The failing-construct set names the thing to fix.

Run by benchmarks/watcher.py once per round after its first successful
device sweep (or by hand: ``python -m benchmarks.mosaic_diag``).  Prints
one JSON line; full tracebacks go to benchmarks/mosaic_diag.log.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LOG = os.path.join(REPO, "benchmarks", "mosaic_diag.log")


def _log(msg: str) -> None:
    with open(LOG, "a", encoding="utf-8") as f:
        f.write(f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] "
                f"{msg}\n")


def _case(name: str, fn) -> dict:
    t0 = time.perf_counter()
    try:
        fn()
        out = {"case": name, "ok": True,
               "s": round(time.perf_counter() - t0, 1)}
    except Exception as e:  # noqa: BLE001 — diagnostic: report, don't die
        _log(f"{name} FAILED:\n{traceback.format_exc()}")
        out = {"case": name, "ok": False,
               "s": round(time.perf_counter() - t0, 1),
               "error": f"{type(e).__name__}: {e}"[:600]}
    _log(f"{name}: {json.dumps(out)}")
    return out


# Local logic check without hardware: TPUNODE_DIAG_INTERPRET=1 runs the
# pallas cases in interpret mode (tests/test_benchmarks.py uses it).
_INTERPRET = os.environ.get("TPUNODE_DIAG_INTERPRET") == "1"


def _trivial() -> None:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def add_one(x_ref, o_ref):
        o_ref[...] = x_ref[...] + 1

    x = jnp.zeros((8, 128), jnp.int32)
    y = pl.pallas_call(
        add_one, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_INTERPRET,
    )(x)
    assert int(y.sum()) == 8 * 128


def _field_mul() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    def mul_kernel(a_ref, b_ref, o_ref):
        o_ref[...] = PF.canonical(PF.mul(a_ref[...], b_ref[...]))

    b = 256
    rng = np.random.default_rng(7)
    av = [int(rng.integers(0, 2**63)) for _ in range(b)]
    bv = [int(rng.integers(0, 2**63)) for _ in range(b)]
    a = jnp.asarray(np.stack([F.to_limbs(v) for v in av], axis=1))
    bb = jnp.asarray(np.stack([F.to_limbs(v) for v in bv], axis=1))
    out = pl.pallas_call(
        mul_kernel, out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        interpret=_INTERPRET,
    )(a, bb)
    for i in (0, b - 1):
        got = F.from_limbs(np.asarray(out)[:, i])
        assert got == (av[i] * bv[i]) % F.P, (i, got)


def _field_mul_dot() -> None:
    """The ISSUE-4 dot_general formulation inside a pallas kernel: one
    iota-built (47, 576) scatter contraction (int32 MACs).  Whether
    Mosaic lowers an integer dot_general at all on this toolchain is
    exactly what this case answers — the knob's TPU viability verdict
    (PERF.md roofline section) is blocked on it."""
    from tpunode.verify import field as F

    prev = F.field_modes()
    try:
        F.set_field_modes(mul="dot_general", sqr="half")
        _field_mul()
    finally:
        F.set_field_modes(mul=prev[0], sqr=prev[1])


def _table_build() -> None:
    """The r3-era construct: a VMEM scratch table built with pl.ds
    dynamic stores inside a fori_loop (the kernel's Q-table pattern).
    Passing this while pow_window fails localizes the outage to the
    r4 digit-window construct."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    b = 256

    def kernel(a_ref, o_ref, tab_ref):
        one = jnp.concatenate(
            [jnp.ones((1, b), jnp.int32),
             jnp.zeros((F.NLIMBS - 1, b), jnp.int32)], axis=0)
        a = a_ref[...]
        tab_ref[0] = one
        tab_ref[1] = a

        def step(k, carry):
            tab_ref[pl.ds(k, 1)] = PF.mul(
                tab_ref[pl.ds(k - 1, 1)][0], a)[None]
            return carry

        lax.fori_loop(2, 16, step, 0)
        o_ref[...] = PF.canonical(tab_ref[15])

    rng = np.random.default_rng(11)
    av = [int(rng.integers(1, 2**61)) for _ in range(b)]
    a = jnp.asarray(np.stack([F.to_limbs(v) for v in av], axis=1))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        scratch_shapes=[pltpu.VMEM((16, F.NLIMBS, b), jnp.int32)],
        interpret=_INTERPRET,
    )(a)
    got = F.from_limbs(np.asarray(out)[:, 0])
    assert got == pow(av[0], 15, F.P), got


def _pow_window_impl(smem_digits: bool) -> None:
    """The r4-added construct: windowed constant-exponent pow with the
    digit sequence in a (2, 64) int32 ref read by a dynamic scalar index
    inside the window fori_loop (the kernel's jacobi/Fermat lowering,
    pallas_kernel.py:190-215) — the top suspect for the Mosaic 500s.
    ``smem_digits`` selects the digit ref's memory space: False is the
    r4 original (VMEM — the suspect), True is the canonical SMEM
    placement the kernel now uses; their pass/fail split pins the
    diagnosis."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    b = 256
    exp = (F.P - 1) // 2
    digits = [(exp >> (4 * (63 - w))) & 0xF for w in range(64)]

    def kernel(a_ref, dig_ref, o_ref, powtab_ref):
        one = jnp.concatenate(
            [jnp.ones((1, b), jnp.int32),
             jnp.zeros((F.NLIMBS - 1, b), jnp.int32)], axis=0)
        t = a_ref[...]
        powtab_ref[0] = one
        powtab_ref[1] = t

        def build(k, carry):
            powtab_ref[pl.ds(k, 1)] = PF.mul(
                powtab_ref[pl.ds(k - 1, 1)][0], t)[None]
            return carry

        lax.fori_loop(2, 16, build, 0)

        def window(w, pacc):
            pacc = PF.sqr(PF.sqr(PF.sqr(PF.sqr(pacc))))
            d = dig_ref[0, w]
            sel = None
            for tv in range(16):
                contrib = jnp.where(d == tv, powtab_ref[tv], 0)
                sel = contrib if sel is None else sel + contrib
            return PF.mul(pacc, sel)

        pacc = lax.fori_loop(0, 64, window, one)
        o_ref[...] = PF.canonical(pacc)

    rng = np.random.default_rng(13)
    av = [int(rng.integers(2, 2**61)) ** 2 % F.P for _ in range(b)]  # QRs
    a = jnp.asarray(np.stack([F.to_limbs(v) for v in av], axis=1))
    dig = jnp.asarray(
        np.stack([digits, digits], axis=0).astype(np.int32))
    dig_spec = (
        pl.BlockSpec((2, 64), memory_space=pltpu.SMEM)
        if smem_digits
        else pl.BlockSpec((2, 64))
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        in_specs=[pl.BlockSpec(a.shape), dig_spec],
        scratch_shapes=[pltpu.VMEM((16, F.NLIMBS, b), jnp.int32)],
        interpret=_INTERPRET,
    )(a, dig)
    for i in (0, b - 1):
        got = F.from_limbs(np.asarray(out)[:, i])
        assert got == pow(av[i], exp, F.P) == 1, (i, got)


def _pow_window() -> None:
    _pow_window_impl(smem_digits=False)


def _pow_window_smem() -> None:
    _pow_window_impl(smem_digits=True)


def _mixed_add() -> None:
    """The ISSUE-8 affine-form primitive: curve.pt_add_mixed (complete
    RCB Algorithm 8, 11M+2) with the Mosaic field namespace inside a
    pallas kernel.  Verified projectively: X - x_e*Z ≡ Y - y_e*Z ≡ 0
    (mod p) against host-side affine point addition."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF
    from tpunode.verify.curve import pt_add_mixed
    from tpunode.verify.ecdsa_cpu import GENERATOR, point_add, point_mul

    b = 256
    P1 = point_mul(7, GENERATOR)
    P2 = point_mul(11, GENERATOR)
    E = point_add(P1, P2)

    def kernel(px_ref, py_ref, qx_ref, qy_ref, ex_ref, ey_ref, o_ref):
        one = jnp.concatenate(
            [jnp.ones((1, b), jnp.int32),
             jnp.zeros((F.NLIMBS - 1, b), jnp.int32)], axis=0)
        p = jnp.stack([px_ref[...], py_ref[...], one], axis=0)
        q = jnp.stack([qx_ref[...], qy_ref[...]], axis=0)
        r = pt_add_mixed(p, q, F=PF)
        bad_x = PF.canonical(r[0] - PF.mul(ex_ref[...], r[2]))
        bad_y = PF.canonical(r[1] - PF.mul(ey_ref[...], r[2]))
        o_ref[...] = bad_x + bad_y

    def cols(v):
        return jnp.asarray(
            np.broadcast_to(F.to_limbs(v)[:, None], (F.NLIMBS, b)))

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((F.NLIMBS, b), jnp.int32),
        interpret=_INTERPRET,
    )(cols(P1.x), cols(P1.y), cols(P2.x), cols(P2.y), cols(E.x), cols(E.y))
    assert not np.asarray(out).any(), "mixed add mismatch"


def _batch_inv() -> None:
    """The ISSUE-8 on-device batch inversion composed exactly like the
    affine kernel's: a 16-entry Z column in VMEM scratch, prefix
    products, ONE Fermat ladder (SMEM digit row), suffix pass — then
    z_15 * zinv_15 must canonicalize to 1."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    b = 256
    pm2 = [((F.P - 2) >> (4 * (63 - w))) & 0xF for w in range(64)]

    def kernel(z_ref, dig_ref, o_ref, ztab_ref, ptab_ref, powtab_ref):
        one = jnp.concatenate(
            [jnp.ones((1, b), jnp.int32),
             jnp.zeros((F.NLIMBS - 1, b), jnp.int32)], axis=0)
        z = z_ref[...]
        ztab_ref[1] = one
        ztab_ref[pl.ds(2, 1)] = z[None]

        def zbuild(k, c):
            ztab_ref[pl.ds(k, 1)] = PF.mul(
                ztab_ref[pl.ds(k - 1, 1)][0], z)[None]
            return c

        lax.fori_loop(3, 16, zbuild, 0)
        ptab_ref[1] = one
        ptab_ref[2] = ztab_ref[2]

        def prefix(k, c):
            ptab_ref[pl.ds(k, 1)] = PF.mul(
                ptab_ref[pl.ds(k - 1, 1)][0], ztab_ref[pl.ds(k, 1)][0])[None]
            return c

        lax.fori_loop(3, 16, prefix, 0)
        t = ptab_ref[15]
        powtab_ref[0] = one
        powtab_ref[1] = t

        def pbuild(k, c):
            powtab_ref[pl.ds(k, 1)] = PF.mul(
                powtab_ref[pl.ds(k - 1, 1)][0], t)[None]
            return c

        lax.fori_loop(2, 16, pbuild, 0)

        def window(w, pacc):
            pacc = PF.sqr(PF.sqr(PF.sqr(PF.sqr(pacc))))
            d = dig_ref[0, w]
            sel = None
            for tv in range(16):
                contrib = jnp.where(d == tv, powtab_ref[tv], 0)
                sel = contrib if sel is None else sel + contrib
            return PF.mul(pacc, sel)

        inv = lax.fori_loop(0, 64, window, one)
        # suffix step for entry 15 (the first the real kernel takes):
        # zinv_15 = inv * (z_2..z_14), then z_15 * zinv_15 must be 1
        zinv15 = PF.mul(inv, ptab_ref[14])
        o_ref[...] = PF.canonical(PF.mul(ztab_ref[15], zinv15))

    rng = np.random.default_rng(17)
    zv = [int(rng.integers(2, 2**61)) for _ in range(b)]
    zcol = jnp.asarray(np.stack([F.to_limbs(v) for v in zv], axis=1))
    dig = jnp.asarray(np.array([pm2, pm2], dtype=np.int32))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((F.NLIMBS, b), jnp.int32),
        in_specs=[
            pl.BlockSpec(zcol.shape),
            pl.BlockSpec((2, 64), memory_space=pltpu.SMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((16, F.NLIMBS, b), jnp.int32),
            pltpu.VMEM((16, F.NLIMBS, b), jnp.int32),
            pltpu.VMEM((16, F.NLIMBS, b), jnp.int32),
        ],
        interpret=_INTERPRET,
    )(zcol, dig)
    got = np.asarray(out)
    for i in (0, b - 1):
        assert F.from_limbs(got[:, i]) == 1, (i, F.from_limbs(got[:, i]))


def _pow_descan() -> None:
    """The ISSUE-8 de-scanned pow ladder: 64 UNROLLED windows with
    static digits (plain static powtab indices, no per-digit selects,
    no fori_loop).  XLA-CPU chokes on the program size (the measured
    reason TPUNODE_POW_LADDER defaults to scan); whether Mosaic compiles
    it — and faster than the fori_loop form — is exactly what this case
    answers."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    b = 256
    exp = (F.P - 1) // 2
    digits = [(exp >> (4 * (63 - w))) & 0xF for w in range(64)]

    def kernel(a_ref, o_ref, powtab_ref):
        one = jnp.concatenate(
            [jnp.ones((1, b), jnp.int32),
             jnp.zeros((F.NLIMBS - 1, b), jnp.int32)], axis=0)
        t = a_ref[...]
        powtab_ref[0] = one
        powtab_ref[1] = t
        for k in range(2, 16):  # log-depth static build
            src = powtab_ref[k // 2] if k % 2 == 0 else powtab_ref[k - 1]
            powtab_ref[k] = (
                PF.sqr(src) if k % 2 == 0 else PF.mul(src, t)
            )
        acc = powtab_ref[digits[0]]
        for d in digits[1:]:
            acc = PF.sqr(PF.sqr(PF.sqr(PF.sqr(acc))))
            if d:
                acc = PF.mul(acc, powtab_ref[d])
        o_ref[...] = PF.canonical(acc)

    rng = np.random.default_rng(19)
    av = [int(rng.integers(2, 2**61)) ** 2 % F.P for _ in range(b)]  # QRs
    a = jnp.asarray(np.stack([F.to_limbs(v) for v in av], axis=1))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        scratch_shapes=[pltpu.VMEM((16, F.NLIMBS, b), jnp.int32)],
        interpret=_INTERPRET,
    )(a)
    for i in (0, b - 1):
        got = F.from_limbs(np.asarray(out)[:, i])
        assert got == 1, (i, got)


def _select_tree() -> None:
    """The ISSUE-8 balanced 4-level select tree over a VMEM table ref
    (kernel/pallas _select16 tree mode): select entry d per lane via 15
    bit-resolved wheres; the selected power must equal t^d."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    b = 256

    def kernel(a_ref, d_ref, o_ref, tab_ref):
        one = jnp.concatenate(
            [jnp.ones((1, b), jnp.int32),
             jnp.zeros((F.NLIMBS - 1, b), jnp.int32)], axis=0)
        t = a_ref[...]
        tab_ref[0] = one
        tab_ref[1] = t

        def build(k, c):
            tab_ref[pl.ds(k, 1)] = PF.mul(
                tab_ref[pl.ds(k - 1, 1)][0], t)[None]
            return c

        lax.fori_loop(2, 16, build, 0)
        d = d_ref[...]  # (1, B)
        level = [tab_ref[tv] for tv in range(16)]
        for i in range(4):
            bit = ((d >> i) & 1) == 1
            level = [
                jnp.where(bit, level[2 * j + 1], level[2 * j])
                for j in range(len(level) // 2)
            ]
        o_ref[...] = PF.canonical(level[0])

    rng = np.random.default_rng(23)
    av = [int(rng.integers(2, 2**31)) for _ in range(b)]
    dv = [int(rng.integers(0, 16)) for _ in range(b)]
    a = jnp.asarray(np.stack([F.to_limbs(v) for v in av], axis=1))
    d = jnp.asarray(np.array(dv, dtype=np.int32)[None])
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        scratch_shapes=[pltpu.VMEM((16, F.NLIMBS, b), jnp.int32)],
        interpret=_INTERPRET,
    )(a, d)
    got = np.asarray(out)
    for i in (0, 7, b - 1):
        assert F.from_limbs(got[:, i]) == pow(av[i], dv[i], F.P), i


def _lazy_reduce() -> None:
    """The ISSUE-12 lazy-reduction primitive exactly as curve.py's lazy
    bodies compose it: two bare convolutions (mul_t_wide) accumulated
    wide (acc_add) and paid down with ONE loose reduction — the
    47-sublane intermediates are the construct Mosaic hasn't seen
    before this PR.  canonical(reduce_wide_loose(a·b + c·d)) must equal
    (a*b + c*d) mod p."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    b = 256

    def kernel(a_ref, b_ref, c_ref, d_ref, o_ref):
        w = PF.acc_add(
            PF.mul_t_wide(a_ref[...], b_ref[...]),
            PF.mul_t_wide(c_ref[...], d_ref[...]),
        )
        o_ref[...] = PF.canonical(PF.reduce_wide_loose(w))

    rng = np.random.default_rng(29)
    cols = []
    vals = []
    for _ in range(4):
        v = [int(rng.integers(0, 2**61)) for _ in range(b)]
        vals.append(v)
        cols.append(jnp.asarray(np.stack([F.to_limbs(x) for x in v], axis=1)))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((F.NLIMBS, b), jnp.int32),
        interpret=_INTERPRET,
    )(*cols)
    av, bv, cv, dv = vals
    for i in (0, b - 1):
        got = F.from_limbs(np.asarray(out)[:, i])
        want = (av[i] * bv[i] + cv[i] * dv[i]) % F.P
        assert got == want, (i, got)


def _window5() -> None:
    """The ISSUE-12 5-bit window constructs in one probe: a 32-entry
    VMEM scratch table built with pl.ds stores, a 5-level select tree
    over it (digits in [0, 32)), and a SHARED constant table input —
    (32, L, 1), one copy for all lanes, broadcast against the per-lane
    digit row inside each where (the layout the wb=5 kernel uses for
    G/λG instead of per-lane duplication).  Selected per-lane power
    times selected shared power must equal a^d * g^d mod p."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    b = 256
    g = 0xC0FFEE
    gtab_np = np.stack(
        [F.to_limbs(pow(g, k, F.P))[:, None] for k in range(32)], axis=0
    )  # (32, L, 1): ONE shared copy

    def tree32(entries, d):
        level = list(entries)
        for i in range(5):
            bit = ((d >> i) & 1) == 1
            level = [
                jnp.where(bit, level[2 * j + 1], level[2 * j])
                for j in range(len(level) // 2)
            ]
        return level[0]

    def kernel(a_ref, g_ref, d_ref, o_ref, tab_ref):
        one = jnp.concatenate(
            [jnp.ones((1, b), jnp.int32),
             jnp.zeros((F.NLIMBS - 1, b), jnp.int32)], axis=0)
        t = a_ref[...]
        tab_ref[0] = one
        tab_ref[1] = t

        def build(k, c):
            tab_ref[pl.ds(k, 1)] = PF.mul(
                tab_ref[pl.ds(k - 1, 1)][0], t)[None]
            return c

        lax.fori_loop(2, 32, build, 0)
        d = d_ref[...]  # (1, B)
        mine = tree32([tab_ref[tv] for tv in range(32)], d)
        shared = tree32([g_ref[tv] for tv in range(32)], d)  # (L,1)x(1,B)
        o_ref[...] = PF.canonical(PF.mul(mine, shared))

    rng = np.random.default_rng(31)
    av = [int(rng.integers(2, 2**31)) for _ in range(b)]
    dv = [int(rng.integers(0, 32)) for _ in range(b)]
    a = jnp.asarray(np.stack([F.to_limbs(v) for v in av], axis=1))
    d = jnp.asarray(np.array(dv, dtype=np.int32)[None])
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        in_specs=[
            pl.BlockSpec(a.shape),
            pl.BlockSpec(gtab_np.shape),
            pl.BlockSpec((1, b)),
        ],
        scratch_shapes=[pltpu.VMEM((32, F.NLIMBS, b), jnp.int32)],
        interpret=_INTERPRET,
    )(a, jnp.asarray(gtab_np), d)
    got = np.asarray(out)
    for i in (0, 7, b - 1):
        want = pow(av[i], dv[i], F.P) * pow(g, dv[i], F.P) % F.P
        assert F.from_limbs(got[:, i]) == want, i


def _flagship() -> None:
    import jax.numpy as jnp

    from benchmarks.common import make_triples
    from tpunode.verify.cpu_native import load_native_verifier
    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.kernel import collect_verdicts, prepare_batch
    from tpunode.verify.pallas_kernel import (
        verify_blocked,
        verify_blocked_impl,
    )

    base = make_triples(256)
    prep = prepare_batch(base, pad_to=256)
    args = tuple(jnp.asarray(a) for a in prep.device_args)
    if _INTERPRET:
        out = verify_blocked_impl(*args, interpret=True, block=256)
    else:
        out = verify_blocked(*args)
    got = collect_verdicts(out, len(base))
    native = load_native_verifier()
    expect = (native.verify_batch(base) if native is not None
              else verify_batch_cpu(base))
    assert got == expect, "flagship verdict mismatch"


def main() -> None:
    res: dict = {"diag": "mosaic", "cases": []}
    try:
        import jax

        if _INTERPRET:
            # Env alone is not enough: this box's TPU shim
            # (sitecustomize) force-sets jax_platforms in every process,
            # and a dead tunnel then blocks jax.devices() forever.
            jax.config.update("jax_platforms", "cpu")
        dev = jax.devices()[0]
        res["device"] = f"{getattr(dev, 'platform', '?')}:" \
                        f"{getattr(dev, 'device_kind', '?')}"
        if dev.platform != "tpu" and not _INTERPRET:
            res["error"] = "not a tpu backend; diagnostic meaningless"
            print(json.dumps(res))
            return
    except Exception as e:  # noqa: BLE001
        res["error"] = f"backend init failed: {e}"[:300]
        print(json.dumps(res))
        return
    for name, fn in (("trivial", _trivial), ("field_mul", _field_mul),
                     ("field_mul_dot", _field_mul_dot),
                     ("table_build", _table_build),
                     ("pow_window", _pow_window),
                     ("pow_window_smem", _pow_window_smem),
                     ("mixed_add", _mixed_add),
                     ("batch_inv", _batch_inv),
                     ("pow_descan", _pow_descan),
                     ("select_tree", _select_tree),
                     ("lazy_reduce", _lazy_reduce),
                     ("window5", _window5),
                     ("flagship", _flagship)):
        out = _case(name, fn)
        res["cases"].append(out)
        if name == "trivial" and not out["ok"]:
            res["verdict"] = "infra: compile helper broken for ALL pallas"
            break
    else:
        oks = {c["case"]: c["ok"] for c in res["cases"]}
        failed = [c["case"] for c in res["cases"] if not c["ok"]]
        if all(oks.values()):
            res["verdict"] = "mosaic healthy (outage over?)"
        elif failed == ["pow_window"]:
            # The expected signature once the kernel's SMEM placement
            # works: only the VMEM digit-read probe fails.
            res["verdict"] = ("repo: VMEM dynamic scalar digit read "
                              "confirmed as cause; SMEM kernel fix works")
        elif failed == ["field_mul_dot"]:
            # Not an outage: the default shift_add programs are healthy;
            # Mosaic just can't lower the experimental int32 dot_general
            # formulation (the PERF.md MXU-path verdict wants this fact).
            res["verdict"] = ("healthy; int32 dot_general formulation "
                              "not lowerable (MXU knob stays off on TPU)")
        elif "select_tree" in failed:
            # NOT a calming verdict: the select tree is the DEFAULT
            # (TPUNODE_SELECT16=tree rides in the flagship), so a
            # failing tree lowering takes the pallas headline down with
            # it — the operator escape hatch is the onehot knob.
            res["verdict"] = ("repo: DEFAULT select-tree lowering "
                              "failing — set TPUNODE_SELECT16=onehot to "
                              "restore the flagship; failing = "
                              + ",".join(failed))
        elif failed and set(failed) <= {"field_mul_dot", "mixed_add",
                                        "batch_inv", "pow_descan",
                                        "lazy_reduce", "window5"}:
            # Default programs healthy; only OFF-BY-DEFAULT experimental
            # primitives fail — the corresponding knobs stay off on TPU
            # (PERF.md records which).
            res["verdict"] = ("healthy; experimental primitives failing: "
                              + ",".join(failed))
        elif oks.get("trivial"):
            res["verdict"] = f"repo: failing constructs = {','.join(failed)}"
    print(json.dumps(res))


if __name__ == "__main__":
    main()
