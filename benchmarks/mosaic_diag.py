"""Bounded Mosaic-outage diagnostic (r5).

The axon remote-compile helper is 500ing on every Pallas program this
round (``MosaicError: .../remote_compile: HTTP 500``) while plain XLA
programs compile and run on the same device.  This script discriminates
the two possible causes when an uptime window allows:

1. ``trivial``  — a 2-line Pallas add kernel.  If THIS fails, the compile
   helper is broken for all Mosaic programs (infra outage; nothing to fix
   in-repo).
2. ``field_mul`` — one pallas_field.mul over a (24, 256) block, the verify
   kernel's core op.  Separates "our field formulas" from "any kernel".
3. ``flagship`` — the real ``verify_blocked`` at batch 256 (one block).
   If only this fails, something the r4 lanes added trips the helper and
   an in-repo fix is worth hunting.

Run by benchmarks/watcher.py once per round after its first successful
device sweep (or by hand: ``python -m benchmarks.mosaic_diag``).  Prints
one JSON line; full tracebacks go to benchmarks/mosaic_diag.log.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LOG = os.path.join(REPO, "benchmarks", "mosaic_diag.log")


def _log(msg: str) -> None:
    with open(LOG, "a", encoding="utf-8") as f:
        f.write(f"[{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}] "
                f"{msg}\n")


def _case(name: str, fn) -> dict:
    t0 = time.perf_counter()
    try:
        fn()
        out = {"case": name, "ok": True,
               "s": round(time.perf_counter() - t0, 1)}
    except Exception as e:  # noqa: BLE001 — diagnostic: report, don't die
        _log(f"{name} FAILED:\n{traceback.format_exc()}")
        out = {"case": name, "ok": False,
               "s": round(time.perf_counter() - t0, 1),
               "error": f"{type(e).__name__}: {e}"[:600]}
    _log(f"{name}: {json.dumps(out)}")
    return out


# Local logic check without hardware: TPUNODE_DIAG_INTERPRET=1 runs the
# pallas cases in interpret mode (tests/test_benchmarks.py uses it).
_INTERPRET = os.environ.get("TPUNODE_DIAG_INTERPRET") == "1"


def _trivial() -> None:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def add_one(x_ref, o_ref):
        o_ref[...] = x_ref[...] + 1

    x = jnp.zeros((8, 128), jnp.int32)
    y = pl.pallas_call(
        add_one, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_INTERPRET,
    )(x)
    assert int(y.sum()) == 8 * 128


def _field_mul() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    from tpunode.verify import field as F
    from tpunode.verify import pallas_field as PF

    def mul_kernel(a_ref, b_ref, o_ref):
        o_ref[...] = PF.canonical(PF.mul(a_ref[...], b_ref[...]))

    b = 256
    rng = np.random.default_rng(7)
    av = [int(rng.integers(0, 2**63)) for _ in range(b)]
    bv = [int(rng.integers(0, 2**63)) for _ in range(b)]
    a = jnp.asarray(np.stack([F.to_limbs(v) for v in av], axis=1))
    bb = jnp.asarray(np.stack([F.to_limbs(v) for v in bv], axis=1))
    out = pl.pallas_call(
        mul_kernel, out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        interpret=_INTERPRET,
    )(a, bb)
    for i in (0, b - 1):
        got = F.from_limbs(np.asarray(out)[:, i])
        assert got == (av[i] * bv[i]) % F.P, (i, got)


def _flagship() -> None:
    import jax.numpy as jnp

    from benchmarks.common import make_triples
    from tpunode.verify.cpu_native import load_native_verifier
    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.kernel import collect_verdicts, prepare_batch
    from tpunode.verify.pallas_kernel import (
        verify_blocked,
        verify_blocked_impl,
    )

    base = make_triples(256)
    prep = prepare_batch(base, pad_to=256)
    args = tuple(jnp.asarray(a) for a in prep.device_args)
    if _INTERPRET:
        out = verify_blocked_impl(*args, interpret=True, block=256)
    else:
        out = verify_blocked(*args)
    got = collect_verdicts(out, len(base))
    native = load_native_verifier()
    expect = (native.verify_batch(base) if native is not None
              else verify_batch_cpu(base))
    assert got == expect, "flagship verdict mismatch"


def main() -> None:
    res: dict = {"diag": "mosaic", "cases": []}
    try:
        import jax

        if _INTERPRET:
            # Env alone is not enough: this box's TPU shim
            # (sitecustomize) force-sets jax_platforms in every process,
            # and a dead tunnel then blocks jax.devices() forever.
            jax.config.update("jax_platforms", "cpu")
        dev = jax.devices()[0]
        res["device"] = f"{getattr(dev, 'platform', '?')}:" \
                        f"{getattr(dev, 'device_kind', '?')}"
        if dev.platform != "tpu" and not _INTERPRET:
            res["error"] = "not a tpu backend; diagnostic meaningless"
            print(json.dumps(res))
            return
    except Exception as e:  # noqa: BLE001
        res["error"] = f"backend init failed: {e}"[:300]
        print(json.dumps(res))
        return
    for name, fn in (("trivial", _trivial), ("field_mul", _field_mul),
                     ("flagship", _flagship)):
        out = _case(name, fn)
        res["cases"].append(out)
        if name == "trivial" and not out["ok"]:
            res["verdict"] = "infra: compile helper broken for ALL pallas"
            break
    else:
        oks = {c["case"]: c["ok"] for c in res["cases"]}
        if all(oks.values()):
            res["verdict"] = "mosaic healthy (outage over?)"
        elif oks.get("trivial") and not oks.get("flagship"):
            res["verdict"] = ("repo: flagship kernel trips the helper"
                              + ("" if oks.get("field_mul")
                                 else " (field ops already fail)"))
    print(json.dumps(res))


if __name__ == "__main__":
    main()
