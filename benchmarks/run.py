"""The five BASELINE.json benchmark configurations.

Usage::

    python -m benchmarks.run config2          # one config
    python -m benchmarks.run all              # everything runnable here

Each config prints exactly one JSON line (driver bench.py schema plus
detail fields).  Workloads are synthetic but shaped like the targets
(BASELINE.md: zero-egress environment, no real mainnet data), generated
deterministically by benchmarks.txgen and cached under benchmarks/data.

Environment knobs:
    TPUNODE_BENCH_SMALL=1   shrink every config (CI / CPU-jax smoke runs)
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

from benchmarks.common import (
    cpu_single_core_bench,
    device_kind as _device_kind,
    make_triples as _make_triples,
    tile as _tile,
)

SMALL = os.environ.get("TPUNODE_BENCH_SMALL") == "1"


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


# --- config 1: block-800000-shaped tx set, CPU single-core baseline -------


def config1() -> None:
    """Single big-block tx set through the C++ CPU verifier (single core).
    This IS the baseline reference point (BASELINE.md config 1): mainnet
    block 800000 carried ~3,700 inputs; we use a 4,096-signature stand-in."""
    from tpunode.txverify import extract_sig_items
    from benchmarks.txgen import gen_signed_txs

    n_txs = 64 if SMALL else 2048  # 2 sigs each -> 4096 sigs
    txs = gen_signed_txs(n_txs, inputs_per_tx=2, seed=0x800000, invalid_every=0)
    items = []
    for tx in txs:
        its, _ = extract_sig_items(tx)
        items.extend((i.pubkey, i.z, i.r, i.s) for i in its)
    rate, engine, out = cpu_single_core_bench(items)
    assert all(out), "baseline block must verify fully"
    _emit(
        {
            "metric": "config1_block800k_cpu_verify",
            "value": round(rate, 1),
            "unit": "sigs/sec/core",
            "vs_baseline": 1.0,
            "engine": engine,
            "sigs": len(items),
            "wall_s": round(len(items) / rate, 4),
        }
    )


# --- config 2: synthetic 10k batch on the device --------------------------


def config2() -> None:
    """10k random triples through the device kernel at batch 4096
    (BASELINE.md config 2; the repo-root bench.py is this config's
    single-batch steady-state variant)."""
    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.kernel import (
        collect_verdicts,
        dispatch_batch_tpu,
        verify_batch_tpu,
    )

    total = 640 if SMALL else 10_240
    batch = 128 if SMALL else 4096
    uniq = _make_triples(min(total, 512))
    items = _tile(uniq, total)
    # correctness first: one chunk vs oracle (also compiles outside timing)
    assert verify_batch_tpu(items[:64], pad_to=batch) == verify_batch_cpu(
        items[:64]
    )
    # steady state: pipelined dispatch — chunk N+1 host-preps while chunk N
    # runs on the device (the engine's production pattern)
    t0 = time.perf_counter()
    n = 0
    pending = []
    for off in range(0, total, batch):
        chunk = items[off : off + batch]
        pending.append(dispatch_batch_tpu(chunk, pad_to=batch))
        n += len(chunk)
    for p in pending:
        collect_verdicts(*p)
    dt = time.perf_counter() - t0

    cpu_rate, cpu_engine, _ = cpu_single_core_bench(uniq[:256])
    _emit(
        {
            "metric": "config2_synthetic10k_device_verify",
            "value": round(n / dt, 1),
            "unit": "sigs/sec/chip",
            "vs_baseline": round(n / dt / cpu_rate, 2),
            "device": _device_kind(),
            "sigs": n,
            "batch": batch,
            "wall_s": round(dt, 4),
            "baseline_engine": cpu_engine,
            "note": "includes host prep each batch (end-to-end dispatch)",
        }
    )


# --- config 3: IBD replay from a header-store snapshot --------------------


def config3() -> None:
    """IBD replay (BASELINE.md config 3): parse stored blocks, extract
    signatures, stream through the verify engine in fixed 4096 batches;
    consensus (header connect) runs alongside, and TPU verdicts are checked
    against the CPU oracle on a sample."""
    from tpunode.headers import MemoryHeaderStore, connect_blocks
    from tpunode.params import BCH_REGTEST
    from tpunode.txverify import extract_sig_items, intra_block_amounts
    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.engine import VerifyConfig, VerifyEngine
    from benchmarks.txgen import gen_chain

    n_blocks = 50 if SMALL else 1000
    # denser than the old 8 txs/block so signature volume is meaningful;
    # on a 1-core host the end-to-end rate is bounded by Python ingest
    # (parse/extract/sighash), so the emitted line also reports the verify
    # engine's own throughput within the replay
    txs_per_block = 2 if SMALL else 64
    batch = 128 if SMALL else 4096
    blocks = gen_chain(
        BCH_REGTEST,
        n_blocks,
        txs_per_block,
        cache=f"ibd_{n_blocks}x{txs_per_block}.bin",
        segwit_every=4,  # every 4th tx is a P2WPKH spend: BIP143 end-to-end
    )

    def block_items(b):
        outs = intra_block_amounts(b.txs)
        items = []
        for tx in b.txs:
            amounts = {
                idx: outs[(ti.prevout.txid, ti.prevout.index)]
                for idx, ti in enumerate(tx.inputs)
                if (ti.prevout.txid, ti.prevout.index) in outs
            }
            its, _ = extract_sig_items(tx, prevout_amounts=amounts or None)
            items.extend((i.pubkey, i.z, i.r, i.s) for i in its)
        return items

    async def replay() -> tuple[int, float, int]:
        engine = VerifyEngine(VerifyConfig(batch_size=batch, max_wait=0.002))
        store = MemoryHeaderStore(BCH_REGTEST)
        sigs = 0
        t0 = time.perf_counter()
        async with engine:
            pending = []
            now = int(time.time())
            for b in blocks:
                nodes, best = connect_blocks(store, BCH_REGTEST, now, [b.header])
                store.add_headers(nodes)
                store.set_best(best)
                items = block_items(b)
                if items:
                    sigs += len(items)
                    pending.append(asyncio.ensure_future(engine.verify(items)))
            results = await asyncio.gather(*pending)
            dt = time.perf_counter() - t0
            flat = [v for r in results for v in r]
            assert all(flat), "IBD replay signatures must all verify"
            # consensus-identical check on a sample vs the oracle
            sample_items = []
            for b in blocks[:2]:
                sample_items.extend(block_items(b))
            assert verify_batch_cpu(sample_items) == [True] * len(sample_items)
            return sigs, dt, store.get_best().height

    from tpunode.metrics import metrics as _metrics

    v0 = _metrics.get("verify.seconds") or 0.0
    sigs, dt, height = asyncio.run(replay())
    verify_s = (_metrics.get("verify.seconds") or 0.0) - v0
    _emit(
        {
            "metric": "config3_ibd_replay",
            "value": round(dt, 3),
            "unit": "seconds_wall",
            "vs_baseline": round(sigs / dt, 1),
            "blocks": len(blocks),
            "height": height,
            "sigs": sigs,
            "sigs_per_sec": round(sigs / dt, 1),
            "verify_engine_sigs_per_sec": (
                round(sigs / verify_s, 1) if verify_s else None
            ),
            "note": "end-to-end wall incl. header consensus + pure-Python "
                    "tx parse/extract/sighash on a 1-core host; the engine "
                    "rate is the verify path alone",
            "device": _device_kind(),
        }
    )


# --- config 4: mempool firehose via 8 fake peers --------------------------


def config4() -> None:
    """Mempool firehose (BASELINE.md config 4): a full Node with the verify
    hook enabled, 8 in-process wire-speaking peers streaming tx gossip;
    measures end-to-end TxVerdict throughput through the event bus."""
    from tpunode.actors import Publisher
    from tpunode.node import Node, NodeConfig, TxVerdict
    from tpunode.params import BCH_REGTEST
    from tpunode.store import MemoryKV
    from tpunode.verify.engine import VerifyConfig
    from tpunode.wire import MsgTx, encode_message
    from benchmarks.txgen import gen_signed_txs
    from tests.fakenet import QueueConnection, _fake_remote

    import contextlib

    n_peers = 2 if SMALL else 8
    n_txs = 40 if SMALL else 1024  # unique; tiled across peers
    duration = 3.0 if SMALL else 15.0
    batch = 128 if SMALL else 4096
    # invalid_every must not share a phase with segwit_every (64 % 4 == 0
    # would make EVERY corrupted tx segwit, losing legacy invalid coverage)
    txs = gen_signed_txs(
        n_txs, inputs_per_tx=2, seed=0xF12E, invalid_every=63, segwit_every=4
    )
    # The firehose streams single txs (no block context), so BIP143 amounts
    # come through the embedder hook — config4 exercises that channel.
    from tpunode.txverify import intra_block_amounts as _iba

    prevouts = _iba(txs)

    async def run() -> tuple[int, int, float]:
        from tests import fixtures

        blocks = fixtures.all_blocks()
        net = BCH_REGTEST

        def firehose_connect():
            @contextlib.asynccontextmanager
            async def factory():
                to_node: asyncio.Queue = asyncio.Queue()
                from_node: asyncio.Queue = asyncio.Queue()
                remote = asyncio.ensure_future(
                    _fake_remote(net, blocks, to_node, from_node)
                )

                async def pump():
                    await asyncio.sleep(0.25)  # let the handshake finish first
                    i = 0
                    while True:
                        msg = MsgTx(txs[i % len(txs)])
                        to_node.put_nowait(encode_message(net, msg))
                        i += 1
                        if i % 64 == 0:
                            await asyncio.sleep(0.001)

                pumper = asyncio.ensure_future(pump())
                try:
                    yield QueueConnection(to_node, from_node)
                finally:
                    pumper.cancel()
                    remote.cancel()
                    for t in (pumper, remote):
                        with contextlib.suppress(
                            asyncio.CancelledError, Exception
                        ):
                            await t

            return factory

        pub = Publisher(name="firehose")
        cfg = NodeConfig(
            net=net,
            store=MemoryKV(),
            pub=pub,
            peers=[f"192.0.2.{i}:8333" for i in range(1, n_peers + 1)],
            discover=False,
            max_peers=n_peers,
            connect=lambda sa: firehose_connect(),
            verify=VerifyConfig(batch_size=batch, max_wait=0.005),
            prevout_lookup=lambda txid, vout: prevouts.get((txid, vout)),
        )
        verdicts = 0
        sigs = 0
        async with pub.subscription() as events:
            async with Node(cfg):
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < duration:
                    try:
                        ev = await asyncio.wait_for(events.receive(), 2.0)
                    except asyncio.TimeoutError:
                        continue
                    if isinstance(ev, TxVerdict):
                        verdicts += 1
                        sigs += len(ev.verdicts)
                dt = time.perf_counter() - t0
        return verdicts, sigs, dt

    verdicts, sigs, dt = asyncio.run(run())
    _emit(
        {
            "metric": "config4_mempool_firehose",
            "value": round(sigs / dt, 1),
            "unit": "sigs/sec_end_to_end",
            "vs_baseline": round(verdicts / dt, 1),
            "peers": n_peers,
            "tx_verdicts": verdicts,
            "sigs": sigs,
            "wall_s": round(dt, 2),
            "device": _device_kind(),
        }
    )


# --- config 5: BCH 32 MB-block stress, multi-chip -------------------------


def config5() -> None:
    """32 MB-block stress (BASELINE.md config 5): ~150k signatures (tiled
    from a unique pool — device work is identical) verified via shard_map
    over every available chip; on the single-chip dev box the mesh has one
    device, on CPU-jax runs set XLA_FLAGS=--xla_force_host_platform_device_count=8."""
    import jax

    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.multichip import make_mesh, verify_batch_sharded

    total = 1024 if SMALL else 153_600
    uniq = _make_triples(512 if not SMALL else 64, seed=0x32B)
    items = _tile(uniq, total)
    mesh = make_mesh()
    n_dev = mesh.devices.size
    # correctness on a slice
    assert verify_batch_sharded(items[: 4 * n_dev], mesh=mesh) == verify_batch_cpu(
        items[: 4 * n_dev]
    )
    expected = _tile([bool(b) for b in verify_batch_cpu(uniq)], total)
    # warm (compile) outside the timed window, then time steady state: the
    # 32MB-block config measures sustained verify throughput, not XLA
    t0 = time.perf_counter()
    out = verify_batch_sharded(items, mesh=mesh)
    compile_s = time.perf_counter() - t0
    assert out == expected
    t0 = time.perf_counter()
    out = verify_batch_sharded(items, mesh=mesh)
    dt = time.perf_counter() - t0
    assert out == expected
    _emit(
        {
            "metric": "config5_32mb_block_multichip",
            "value": round(total / dt, 1),
            "unit": "sigs/sec_total",
            "vs_baseline": round(total / dt / max(1, n_dev), 1),
            "devices": n_dev,
            "device": _device_kind(),
            "sigs": total,
            "wall_s": round(dt, 3),
            "first_call_s": round(compile_s, 3),
        }
    )


CONFIGS = {
    "config1": config1,
    "config2": config2,
    "config3": config3,
    "config4": config4,
    "config5": config5,
}


def main(argv: list[str]) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # Honor JAX_PLATFORMS even where a sitecustomize shim force-sets the
    # platform list (this box's TPU tunnel does): pin it via jax.config
    # before the first device use.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    which = argv[0] if argv else "all"
    names = list(CONFIGS) if which == "all" else [which]
    for name in names:
        CONFIGS[name]()


if __name__ == "__main__":
    main(sys.argv[1:])
