"""The five BASELINE.json benchmark configurations.

Usage::

    python -m benchmarks.run config2          # one config
    python -m benchmarks.run all              # everything runnable here

Each config prints exactly one JSON line (driver bench.py schema plus
detail fields).  Workloads are synthetic but shaped like the targets
(BASELINE.md: zero-egress environment, no real mainnet data), generated
deterministically by benchmarks.txgen and cached under benchmarks/data.

Environment knobs:
    TPUNODE_BENCH_SMALL=1   shrink every config (CI / CPU-jax smoke runs)
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

from benchmarks.common import (
    cpu_single_core_bench,
    device_kind as _device_kind,
    make_triples as _make_triples,
    tile as _tile,
)

SMALL = os.environ.get("TPUNODE_BENCH_SMALL") == "1"


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


# --- config 1: block-800000-shaped tx set, CPU single-core baseline -------



def _device_batch_override() -> int:
    """TPUNODE_DEVICE_BATCH, or 0 when unset/invalid (never raises: a bad
    knob must not kill a config before its JSON line)."""
    raw = os.environ.get("TPUNODE_DEVICE_BATCH", "").strip()
    if not raw:
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        print(f"[run] ignoring bad TPUNODE_DEVICE_BATCH={raw!r}",
              file=sys.stderr)
        return 0


def _verify_cfg(**kw):
    """VerifyConfig with an optional TPUNODE_DEVICE_BATCH override.

    The watcher sets it during a Mosaic outage: the engine then falls
    back to the XLA program, whose 32768-shape server-side compile could
    stall warmup past the config budget — a modest steady-state shape
    (XLA throughput plateaus by 8192 anyway, PERF.md r3 table) keeps the
    device run inside its watchdog."""
    from tpunode.verify.engine import VerifyConfig

    db = _device_batch_override()
    if db:
        kw["device_batch"] = db
    return VerifyConfig(**kw)


def _kernel_provenance() -> dict:
    """Outage provenance for device-config rows in device_runs.jsonl: an
    XLA-fallback run must be distinguishable from a pallas steady-state
    one (review r5)."""
    out = {}
    try:
        from tpunode.verify.kernel import pallas_broken

        if pallas_broken():
            out["pallas_broken"] = True
    except Exception:
        pass
    db = _device_batch_override()
    if db:
        out["device_batch_override"] = db
    return out

def config1() -> None:
    """Single big-block tx set through the C++ CPU verifier (single core).
    This IS the baseline reference point (BASELINE.md config 1): mainnet
    block 800000 carried ~3,700 inputs; we use a ~4k-signature stand-in
    with the realistic script-type mix (P2PKH / P2WPKH / P2SH-P2WPKH /
    P2SH+P2WSH 2-of-3 multisig / ~5% unsupported — VERDICT r3 item 3),
    reporting extraction coverage alongside the verify rate."""
    from tpunode.txverify import (
        combine_verdicts,
        extract_sig_items,
        wants_amount,
    )
    from benchmarks.txgen import gen_mixed_txs, synth_prevout

    n_txs = 64 if SMALL else 1536  # ~2.7 sigs/tx in the mix -> ~4k sigs
    txs = gen_mixed_txs(n_txs, seed=0x800000, invalid_every=0)
    items = []
    total_in = coinbase = extracted = sigs = 0
    for tx in txs:
        amounts: dict[int, int] = {}
        scripts: dict[int, bytes] = {}
        for idx, ti in enumerate(tx.inputs):
            if not wants_amount(tx, idx, False):
                continue
            amt, script = synth_prevout(ti.prevout.txid, ti.prevout.index)
            amounts[idx] = amt
            scripts[idx] = script
        its, st = extract_sig_items(
            tx, prevout_amounts=amounts or None, prevout_scripts=scripts or None
        )
        items.extend(its)
        total_in += st.total_inputs
        coinbase += st.coinbase
        extracted += st.extracted
        sigs += st.sigs
    # runs=1: this pass times (and verdicts) the WHOLE block — the
    # median-of-N de-noising lives in bench.py's small-sample baseline
    rate, engine, out = cpu_single_core_bench(
        [i.verify_item for i in items], runs=1
    )
    per_sig = combine_verdicts(items, out)
    assert all(per_sig), "baseline block must verify fully"
    coverage = extracted / (total_in - coinbase)
    assert coverage >= 0.90, f"coverage {coverage:.2f} below target"
    _emit(
        {
            "metric": "config1_block800k_cpu_verify",
            "value": round(rate, 1),
            "unit": "sigs/sec/core",
            "vs_baseline": 1.0,
            "engine": engine,
            "sigs": sigs,
            "candidates": len(items),
            "coverage": round(coverage, 4),
            "wall_s": round(len(items) / rate, 4),
        }
    )


# --- config 2: synthetic 10k batch on the device --------------------------


def config2() -> None:
    """10k random triples through the device kernel at batch 4096
    (BASELINE.md config 2; the repo-root bench.py is this config's
    single-batch steady-state variant)."""
    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.kernel import (
        collect_verdicts,
        dispatch_batch_tpu,
        verify_batch_tpu,
    )

    total = 640 if SMALL else 10_240
    batch = 128 if SMALL else 4096
    uniq = _make_triples(min(total, 512))
    items = _tile(uniq, total)
    # correctness first: one chunk vs oracle (also compiles outside timing).
    # A Mosaic RUNTIME failure surfaces here (compile-stage ones are already
    # handled inside dispatch): mark pallas broken, retry once via XLA.
    from tpunode.verify.kernel import with_mosaic_fallback

    got = with_mosaic_fallback(
        lambda: verify_batch_tpu(items[:64], pad_to=batch), "in config2"
    )
    assert got == verify_batch_cpu(items[:64])
    # steady state: pipelined dispatch — chunk N+1 host-preps while chunk N
    # runs on the device (the engine's production pattern)
    t0 = time.perf_counter()
    n = 0
    pending = []
    for off in range(0, total, batch):
        chunk = items[off : off + batch]
        pending.append(dispatch_batch_tpu(chunk, pad_to=batch))
        n += len(chunk)
    for p in pending:
        collect_verdicts(*p)
    dt = time.perf_counter() - t0

    cpu_rate, cpu_engine, _ = cpu_single_core_bench(uniq[:256])
    _emit(
        {
            "metric": "config2_synthetic10k_device_verify",
            "value": round(n / dt, 1),
            "unit": "sigs/sec/chip",
            "vs_baseline": round(n / dt / cpu_rate, 2),
            "device": _device_kind(),
            "sigs": n,
            "batch": batch,
            "wall_s": round(dt, 4),
            "baseline_engine": cpu_engine,
            "note": "includes host prep each batch (end-to-end dispatch)",
            **_kernel_provenance(),
        }
    )


# --- config 3: IBD replay from a header-store snapshot --------------------


def config3() -> None:
    """IBD replay through the FULL node stack (BASELINE.md config 3;
    VERDICT r3 item 2, rewired for ISSUE 11): a fake wire-speaking peer
    serves a 1000-block mixed-script chain; the chain actor syncs headers
    (real consensus connect), then the node's OWN fetch planner
    (``NodeConfig.ibd``, tpunode/ibd.py) schedules the getdata block
    batches from the UTXO watermark — no embedder pushes or fetch loops
    anywhere — and every block rides the lazy-block native ingest:
    LazyBlock raw bytes -> C++ txx_prevouts (amount oracle rows) ->
    C++ txx_extract (tx-range sharded across the worker pool) ->
    engine.verify_raw -> TxVerdict events -> C++ one-pass UTXO connect.
    No Python tx parsing anywhere on the hot path."""
    import contextlib

    from tpunode.actors import Publisher
    from tpunode.ibd import IbdConfig
    from tpunode.node import Node, NodeConfig, TxVerdict, VerifyShed
    from tpunode.params import BCH_REGTEST
    from tpunode.wire import (
        HEADER_SIZE,
        InvType,
        MsgBlock,
        MsgGetData,
        MsgGetHeaders,
        MsgHeaders,
        MsgPing,
        MsgPong,
        MsgVerAck,
        MsgVersion,
        decode_message,
        decode_message_header,
        encode_message,
    )
    from benchmarks.txgen import gen_chain, synth_prevout
    from tests.fakenet import QueueConnection, _QueueReader

    net = BCH_REGTEST
    n_blocks = 50 if SMALL else 1000
    txs_per_block = 2 if SMALL else 64
    window = 4 if SMALL else 24  # blocks per getdata round-trip
    blocks = gen_chain(
        net,
        n_blocks,
        txs_per_block,
        cache=f"ibd_{n_blocks}x{txs_per_block}.bin",
        mix=True,  # realistic script mix incl. 2-of-3 multisig
    )
    # Pre-encode every wire reply OUTSIDE the timed path: the remote's
    # serialization cost is harness, not node.
    encoded_blocks = {
        b.header.hash: encode_message(net, MsgBlock(b)) for b in blocks
    }
    headers_reply = encode_message(
        net, MsgHeaders(tuple((b.header, len(b.txs)) for b in blocks))
    )

    async def fast_remote(to_node, from_node):
        """Wire-speaking remote with pre-encoded replies."""
        import random as _random
        from tpunode.params import NODE_NETWORK
        from tpunode.wire import NetworkAddress

        local = NetworkAddress.from_host_port("::1", 0, services=NODE_NETWORK)
        ver = MsgVersion(
            version=70012, services=NODE_NETWORK, timestamp=int(time.time()),
            addr_recv=NetworkAddress.from_host_port("::1", 0), addr_from=local,
            nonce=_random.getrandbits(64), user_agent=b"/ibdbench:0/",
            start_height=len(blocks), relay=True,
        )
        to_node.put_nowait(encode_message(net, ver))
        reader = _QueueReader(from_node)
        with contextlib.suppress(EOFError):
            while True:
                raw_header = await reader.read_exact(HEADER_SIZE)
                header = decode_message_header(net, raw_header)
                payload = (
                    await reader.read_exact(header.length) if header.length else b""
                )
                msg = decode_message(net, header, payload)
                if isinstance(msg, MsgPing):
                    to_node.put_nowait(encode_message(net, MsgPong(msg.nonce)))
                elif isinstance(msg, MsgVersion):
                    to_node.put_nowait(encode_message(net, MsgVerAck()))
                elif isinstance(msg, MsgGetHeaders):
                    to_node.put_nowait(headers_reply)
                elif isinstance(msg, MsgGetData):
                    for iv in msg.invs:
                        if iv.type in (InvType.BLOCK, InvType.WITNESS_BLOCK):
                            enc = encoded_blocks.get(iv.hash)
                            if enc is not None:
                                to_node.put_nowait(enc)

    def connect_factory(sa):
        @contextlib.asynccontextmanager
        async def factory():
            to_node: asyncio.Queue = asyncio.Queue()
            from_node: asyncio.Queue = asyncio.Queue()
            task = asyncio.ensure_future(  # asyncsan: disable=raw-spawn (bench harness task, cancelled in finally)
                fast_remote(to_node, from_node)
            )
            try:
                yield QueueConnection(to_node, from_node)
            finally:
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await task

        return factory

    total_txs = n_blocks * (txs_per_block + 1)  # + coinbase per block

    async def replay():
        from tpunode import ChainSynced, PeerConnected
        from tpunode.store import MemoryKV

        pub = Publisher(name="ibd-bench", maxsize=None)  # exact counts: bench bus must be lossless
        cfg = NodeConfig(
            net=net,
            store=MemoryKV(),
            pub=pub,
            peers=["192.0.2.9:8333"],
            discover=False,
            connect=connect_factory,
            verify=_verify_cfg(max_wait=0.004),
            prevout_lookup=synth_prevout,
            utxo=True,
            # the real fetch path (ISSUE 11): the planner walks the chain
            # from the UTXO watermark and paces itself against ingest
            # pressure — the embedder's windowed get_blocks loop is gone
            ibd=IbdConfig(batch_blocks=window, tick_interval=0.02),
        )
        stats = {
            "verdicts": 0, "sigs": 0, "extracted": 0, "noncb_inputs": 0,
            "invalid": 0, "shed": 0,
        }
        done = asyncio.Event()

        async def count_events(events):
            while True:
                ev = await events.receive()
                if isinstance(ev, TxVerdict):
                    stats["verdicts"] += 1
                    stats["sigs"] += len(ev.verdicts)
                    stats["extracted"] += ev.stats.extracted
                    stats["noncb_inputs"] += (
                        ev.stats.total_inputs - ev.stats.coinbase
                    )
                    stats["invalid"] += 0 if ev.valid else 1
                    if stats["verdicts"] >= total_txs:
                        done.set()
                elif isinstance(ev, VerifyShed):
                    stats["shed"] += ev.dropped_txs
        async with pub.subscription() as events:
            async with Node(cfg) as node:
                t0 = time.perf_counter()
                await asyncio.wait_for(
                    events.receive_match(
                        lambda ev: ev.peer if isinstance(ev, PeerConnected) else None
                    ),
                    30,
                )
                await asyncio.wait_for(
                    events.receive_match(
                        lambda ev: ev if isinstance(ev, ChainSynced) else None
                    ),
                    120,
                )
                header_s = time.perf_counter() - t0
                assert node.chain.get_best().height == n_blocks
                counter = asyncio.ensure_future(  # asyncsan: disable=raw-spawn (bench harness task, cancelled in finally)
                    count_events(events)
                )
                try:
                    # the planner is already fetching (it chases the
                    # header tip as headers land); the clock covers the
                    # whole block phase: fetch -> verify -> connect
                    t0 = time.perf_counter()
                    await asyncio.wait_for(done.wait(), 600)

                    async def _wm_catchup():
                        # verdicts all published; the last UTXO connects
                        # trail by one batch
                        while node.utxo.height < n_blocks:
                            await asyncio.sleep(0.005)

                    await asyncio.wait_for(_wm_catchup(), 60)
                    block_s = time.perf_counter() - t0
                    assert node.ibd.stats()["refetches"] == 0
                finally:
                    counter.cancel()
        return header_s, block_s, stats

    header_s, block_s, st = asyncio.run(replay())
    assert st["shed"] == 0, f"backpressure shed {st['shed']} txs"
    assert st["invalid"] == 0, "IBD replay signatures must all verify"
    coverage = st["extracted"] / st["noncb_inputs"]
    assert coverage >= 0.90, f"coverage {coverage:.2f} below target"
    _emit(
        {
            "metric": "config3_ibd_replay",
            "value": round(header_s + block_s, 3),
            "unit": "seconds_wall",
            "vs_baseline": round(st["sigs"] / block_s, 1),
            "blocks": n_blocks,
            "txs": st["verdicts"],
            "sigs": st["sigs"],
            "sigs_per_sec": round(st["sigs"] / block_s, 1),
            "header_sync_s": round(header_s, 3),
            "block_phase_s": round(block_s, 3),
            "coverage": round(coverage, 4),
            "note": "end-to-end through the full node: fetch planner "
                    "(NodeConfig.ibd), wire framing, lazy blocks, sharded "
                    "C++ extract, batch engine, TxVerdict bus, C++ UTXO "
                    "connect",
            "device": _device_kind(),
            **_kernel_provenance(),
        }
    )


# --- config 4: mempool firehose via 8 fake peers --------------------------


def config4() -> None:
    """Mempool firehose (BASELINE.md config 4): a full Node with the verify
    hook enabled, 8 in-process wire-speaking peers streaming pre-encoded tx
    gossip (realistic script mix incl. multisig); measures end-to-end
    TxVerdict throughput through the event bus.  The ingest side batches:
    LazyTx decode (no Python parse) -> tx accumulator -> one C++ extract +
    one engine batch per drain (VERDICT r3 item 5)."""
    from tpunode.actors import Publisher
    from tpunode.node import Node, NodeConfig, TxVerdict
    from tpunode.params import BCH_REGTEST
    from tpunode.store import MemoryKV
    from tpunode.wire import MsgTx, encode_message
    from benchmarks.txgen import gen_mixed_txs, synth_prevout
    from tests.fakenet import QueueConnection, _fake_remote

    import contextlib

    n_peers = 2 if SMALL else 8
    n_txs = 40 if SMALL else 1024  # unique; tiled across peers
    duration = 3.0 if SMALL else 15.0
    batch = 128 if SMALL else 4096
    txs = gen_mixed_txs(n_txs, seed=0xF12E, invalid_every=63, schnorr_every=6)
    net = BCH_REGTEST
    # pre-encode outside the measurement: the pump's serialization cost is
    # harness, not node
    encoded = [encode_message(net, MsgTx(tx)) for tx in txs]

    async def run() -> tuple[int, int, int, float]:
        from tests import fixtures

        blocks = fixtures.all_blocks()

        def firehose_connect():
            @contextlib.asynccontextmanager
            async def factory():
                to_node: asyncio.Queue = asyncio.Queue()
                from_node: asyncio.Queue = asyncio.Queue()
                remote = asyncio.ensure_future(  # asyncsan: disable=raw-spawn (bench harness task, cancelled in finally)
                    _fake_remote(net, blocks, to_node, from_node)
                )

                async def pump():
                    await asyncio.sleep(0.25)  # let the handshake finish first
                    i = 0
                    while True:
                        # pace by queue depth — the in-memory stand-in for
                        # TCP backpressure; an unbounded in-process pump
                        # would otherwise burn the shared core on framing
                        # of messages destined to be shed
                        if to_node.qsize() > 256:
                            await asyncio.sleep(0.002)
                            continue
                        for _ in range(64):
                            to_node.put_nowait(encoded[i % len(encoded)])
                            i += 1
                        await asyncio.sleep(0)

                pumper = asyncio.ensure_future(  # asyncsan: disable=raw-spawn (bench harness task, cancelled in finally)
                    pump()
                )
                try:
                    yield QueueConnection(to_node, from_node)
                finally:
                    pumper.cancel()
                    remote.cancel()
                    for t in (pumper, remote):
                        with contextlib.suppress(
                            asyncio.CancelledError, Exception
                        ):
                            await t

            return factory

        pub = Publisher(name="firehose", maxsize=None)  # exact counts: bench bus must be lossless
        cfg = NodeConfig(
            net=net,
            store=MemoryKV(),
            pub=pub,
            peers=[f"192.0.2.{i}:8333" for i in range(1, n_peers + 1)],
            discover=False,
            max_peers=n_peers,
            connect=lambda sa: firehose_connect(),
            verify=_verify_cfg(batch_size=batch, max_wait=0.005),
            prevout_lookup=synth_prevout,
        )
        verdicts = 0
        sigs = 0
        shed = 0
        # ISSUE 7 satellite: engine warmup (a jax import + device probe
        # in a daemon thread, launched at engine construction) competes
        # for this box's single core — on a slow box it could eat most of
        # the 3s SMALL window and fail the throughput floor.  Let it
        # settle BEFORE the peers (and their pumps) start, so the clock
        # opens on a warmed-up node with an empty bus.
        node = Node(cfg)
        if node.verify_engine is not None:
            await asyncio.to_thread(
                node.verify_engine._warmup_done.wait, 120.0
            )
        async with pub.subscription() as events:
            async with node:
                t0 = time.perf_counter()
                # Batch-drain the bus (ISSUE 7 satellite): popping one
                # event per loop cycle loses a footrace against the
                # firehose on a 1-core box — the window then expires with
                # every TxVerdict still queued behind tens of thousands
                # of republished PeerMessages, reporting 0 verdicts while
                # the engine verified plenty.
                while time.perf_counter() - t0 < duration:
                    drained = events.drain_nowait()
                    if not drained:
                        try:
                            drained = [
                                await asyncio.wait_for(
                                    events.receive(), 0.25
                                )
                            ]
                        except asyncio.TimeoutError:
                            continue
                    for ev in drained:
                        if isinstance(ev, TxVerdict):
                            verdicts += 1
                            sigs += len(ev.verdicts)
                        elif type(ev).__name__ == "VerifyShed":
                            shed += ev.dropped_txs
                dt = time.perf_counter() - t0
        return verdicts, sigs, shed, dt

    verdicts, sigs, shed, dt = asyncio.run(run())
    _emit(
        {
            "metric": "config4_mempool_firehose",
            "value": round(sigs / dt, 1),
            "unit": "sigs/sec_end_to_end",
            "vs_baseline": round(verdicts / dt, 1),
            "peers": n_peers,
            "tx_verdicts": verdicts,
            "sigs": sigs,
            "shed_txs": shed,
            "wall_s": round(dt, 2),
            "device": _device_kind(),
            **_kernel_provenance(),
        }
    )


# --- config 5: BCH 32 MB-block stress, multi-chip -------------------------


def config5() -> None:
    """32 MB-block stress (BASELINE.md config 5): ~150k signatures (tiled
    from a unique pool — device work is identical) dispatched through the
    POD-SCALE FLEET (ISSUE 13): an N-device box runs ``mesh_hosts=N``
    single-chip fleet hosts pulling packed lanes from the work-stealing
    dispatcher — the same scheduler production traffic uses — so the
    first uptime window banks a real multi-chip number end to end (lane
    packing + per-host dispatch included, not just the sharded kernel).
    A 1-device box degrades to the single-host pipeline.  On CPU-jax
    dryruns set XLA_FLAGS=--xla_force_host_platform_device_count=8; the
    cpu-jax backend then stands in for the device (documented dryrun, the
    device field says cpu:*)."""
    import jax

    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.engine import VerifyEngine
    from tpunode.verify.multichip import make_hybrid_mesh, verify_batch_sharded

    total = 1024 if SMALL else 153_600
    uniq = _make_triples(512 if not SMALL else 64, seed=0x32B)
    items = _tile(uniq, total)
    devs = jax.devices()
    n_dev = len(devs)
    platform = getattr(devs[0], "platform", "?")
    # SMALL caps the fleet at 2 hosts: each host's sub-mesh is its own
    # compiled program, and an XLA-CPU smoke run must not pay 8 compiles
    hosts = (min(n_dev, 2) if SMALL else n_dev) if n_dev >= 2 else 0
    # correctness on a slice through the HYBRID mesh program first (the
    # (hosts, 1) grid the fleet's sub-meshes are carved from)
    mesh = make_hybrid_mesh(max(1, hosts or 1), 1)
    assert verify_batch_sharded(items[: 4 * n_dev], mesh=mesh) == verify_batch_cpu(
        items[: 4 * n_dev]
    )
    expected = _tile([bool(b) for b in verify_batch_cpu(uniq)], total)
    # Mosaic-outage knob (via _verify_cfg): the XLA fallback must not
    # compile at the ~150k shape — the engine's lane target (device_batch)
    # already drives fixed-shape chunks, the override just shrinks them.
    batch = 128 if SMALL else 4096
    cfg = _verify_cfg(
        backend="tpu" if platform == "tpu" else "auto",
        batch_size=batch,
        max_wait=0.005,
        pipeline_depth=2,
        min_tpu_batch=1,
        mesh_hosts=hosts,
        # one chip per fleet host (the hybrid rows the engine carves)
        mesh_devices=hosts,
        **({} if platform == "tpu" else {"warmup": False}),
    )
    if SMALL and not _device_batch_override():
        cfg.device_batch = 1024
    eng = VerifyEngine(cfg)
    if platform != "tpu":
        eng._device_state = "ready"  # cpu-jax dryrun: XLA-CPU is the device

    sub = max(batch // 2 + 1, 1)  # odd grain: lanes pack across boundaries

    async def run_all() -> tuple[list, float]:
        async with eng:
            t0 = time.perf_counter()
            futs = [
                # gathered on the next line; supervision would only add
                # registry churn inside the timed window
                asyncio.ensure_future(  # asyncsan: disable=raw-spawn
                    eng.verify(items[off : off + sub])
                )
                for off in range(0, total, sub)
            ]
            got = await asyncio.gather(*futs)
            warm = time.perf_counter() - t0
            assert [v for g in got for v in g] == expected
            # steady state AFTER the compile-bearing first pass
            t0 = time.perf_counter()
            futs = [
                asyncio.ensure_future(  # asyncsan: disable=raw-spawn
                    eng.verify(items[off : off + sub])
                )
                for off in range(0, total, sub)
            ]
            got = await asyncio.gather(*futs)
            dt = time.perf_counter() - t0
            assert [v for g in got for v in g] == expected
            return [warm, dt], eng.stats()

    (compile_s, dt), stats = asyncio.run(run_all())
    fleet = stats.get("fleet") or {}
    _emit(
        {
            "metric": "config5_32mb_block_multichip",
            "value": round(total / dt, 1),
            "unit": "sigs/sec_total",
            "vs_baseline": round(total / dt / max(1, n_dev), 1),
            "devices": n_dev,
            "fleet_hosts": hosts,
            "steals": fleet.get("steals", 0),
            "device": _device_kind(),
            "sigs": total,
            "wall_s": round(dt, 3),
            "first_call_s": round(compile_s, 3),
            **_kernel_provenance(),
        }
    )


CONFIGS = {
    "config1": config1,
    "config2": config2,
    "config3": config3,
    "config4": config4,
    "config5": config5,
}


def main(argv: list[str]) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # Honor JAX_PLATFORMS even where a sitecustomize shim force-sets the
    # platform list (this box's TPU tunnel does): pin it via jax.config
    # before the first device use.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    which = argv[0] if argv else "all"
    names = list(CONFIGS) if which == "all" else [which]
    for name in names:
        CONFIGS[name]()


if __name__ == "__main__":
    main(sys.argv[1:])
