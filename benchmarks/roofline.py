"""Roofline / MFU model for the batch verify kernel, derived from the
LIVE kernel (ISSUE 4 tentpole (a)).

Answers the question VERDICT r5 said the perf story was missing: not
"faster than one CPU core" but **what fraction of the chip** the measured
rates use, and which resource bounds each program.  Three layers, each
derived from the code it describes (no hand-maintained constants that can
drift):

1. **Field-op counts per verify, per algorithm** — the audited RCB
   formulas (`curve.pt_add` / `curve.pt_double`) are executed with a
   counting field namespace, and the per-program totals are assembled
   from `verify/kernel.py`'s actual structure (WINDOWS, the half-scalar
   count from `_DEVICE_FIELDS`, table lengths `2**WINDOW_BITS`, the
   64-digit constant-exponent pow ladders).

2. **Limb ops per field op** — MAC counts come from `field.py`'s live
   pair tables (`len(_MUL_PAIRS)` = 576 for mul, `len(_SQR_PAIRS)` = 300
   for the dedicated sqr), and TOTAL integer vector ops (muls + adds +
   shifts + masks, i.e. what the VPU actually executes including every
   carry/fold round) come from an independent jaxpr walk of the live
   field functions — the structural model cannot drift from the code.

3. **Chip model** — peak numbers for the target part (v5e by default:
   394 int8 TOPS on the MXUs is the datasheet number; the VPU int32 peak
   is an ESTIMATE from lanes x clock x issue width, labeled as such) give
   ideal rates; measured rates divide into utilization.

Run (CPU-only, never touches the tunnel; tracing only, no compiles):

    JAX_PLATFORMS=cpu python -m benchmarks.roofline            # JSON
    JAX_PLATFORMS=cpu python -m benchmarks.roofline --markdown # PERF.md tables

Tested in tests/test_benchmarks.py (op counts pinned against the RCB
paper's 12M for addition and the jaxpr cross-check).
"""

from __future__ import annotations

import collections
import json
import math
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# ---------------------------------------------------------------------------
# Layer 1: field-op counts from the live formulas
# ---------------------------------------------------------------------------


class CountingField:
    """Field namespace that counts mul/sqr calls while delegating to the
    real implementation — `curve`'s formulas take the namespace as their
    ``F=`` parameter, so the counts come from executing the audited code,
    not from reading it.

    The ISSUE 12 lazy pipeline adds the wide-accumulator ops: WIDE_OPS
    are limb convolutions (mul-like work, same MACs as their eager
    twins), TAIL_OPS are the carry/fold machinery (reductions, hoisted
    tighten rounds, wide sums — zero MACs, all carry/fold vector ops)."""

    OPS = ("mul", "mul_t", "sqr", "sqr_t", "mul_small_red")
    WIDE_OPS = ("mul_wide", "mul_t_wide", "sqr_wide", "sqr_t_wide")
    TAIL_OPS = ("reduce_wide", "reduce_wide_loose", "tighten", "acc_add")
    ALL_OPS = OPS + WIDE_OPS + TAIL_OPS

    def __init__(self, base):
        self._base = base
        self.counts = collections.Counter()

    def __getattr__(self, name):
        attr = getattr(self._base, name)
        if name in self.ALL_OPS:
            def counted(*a, _attr=attr, _name=name, **kw):
                self.counts[_name] += 1
                return _attr(*a, **kw)

            return counted
        return attr


def _point_op_counts():
    """(pt_add, pt_double, pt_add_mixed) counts by running the live
    formulas — the mixed add (RCB'16 Algorithm 8, ISSUE 8) is the affine
    window loop's addition; its 11M+2 must pin one full mul under the
    projective add's 12M+2."""
    import jax.numpy as jnp

    from tpunode.verify import field as F
    from tpunode.verify.curve import pt_add, pt_add_mixed, pt_double

    one = jnp.asarray(F.ONE)
    p = jnp.stack([one, one, one], axis=0)
    q2 = jnp.stack([one, one], axis=0)
    cf = CountingField(F)
    pt_add(p, p, F=cf)
    add_counts = dict(cf.counts)
    cf = CountingField(F)
    pt_double(p, F=cf)
    dbl_counts = dict(cf.counts)
    cf = CountingField(F)
    pt_add_mixed(p, q2, F=cf)
    mixed_counts = dict(cf.counts)
    return add_counts, dbl_counts, mixed_counts


def _batch_inversion_counts():
    """Field-op counts of the affine Q-table batch normalization
    (kernel._normalize_q_table: prefix/suffix products + per-entry X/Y
    scaling), by EXECUTING the live helper with a counting namespace at
    the ACTIVE table size (2^window_bits entries).  The shared Fermat
    ladder is counted separately (`_pow_ladder_model`) — the stub
    pow_const here contributes zero ops."""
    import jax.numpy as jnp

    from tpunode.verify import field as F
    from tpunode.verify import kernel as K

    one = jnp.asarray(F.ONE)
    qt = jnp.stack(
        [jnp.stack([one, one, one], axis=0)] * (1 << K.window_bits()),
        axis=0,
    )
    cf = CountingField(F)
    K._normalize_q_table(qt, F=cf, pow_const=lambda t, d: t)
    return dict(cf.counts)


def _pow_ladder_model(digits) -> collections.Counter:
    """Field-op counts of one constant-exponent pow ladder under the
    ACTIVE ladder mode (kernel.pow_ladder_mode()).

    ``scan``: 14 sequential table muls, then per digit window 4
    squarings + 1 table mul.  ``unroll`` (de-scanned, ISSUE 8 lever 2):
    log-depth table build (7 sqr + 7 mul), the MSB window seeds the
    accumulator for free, zero digits skip their mul."""
    from tpunode.verify import kernel as K

    tab_entries = 1 << K.WINDOW_BITS
    n = len(digits)
    if K.pow_ladder_mode() == "scan":
        return collections.Counter(
            {"mul": (tab_entries - 2) + n, "sqr": K.WINDOW_BITS * n}
        )
    c = collections.Counter()
    for k in range(2, tab_entries):
        c["sqr" if k % 2 == 0 else "mul"] += 1
    c["sqr"] += K.WINDOW_BITS * (n - 1)
    c["mul"] += sum(1 for d in list(digits)[1:] if int(d))
    return c


def _q_table_build_model(add_c: dict, dbl_c: dict) -> collections.Counter:
    """Field-op counts of the on-device Q-table build under the ACTIVE
    ladder mode and window width: ``scan`` = 2^wb - 2 sequential
    complete adds; ``unroll`` = a log-depth double-and-add chain (fewer
    muls AND a much shorter critical path)."""
    from tpunode.verify import kernel as K

    tab_entries = 1 << K.window_bits()
    if K.pow_ladder_mode() == "scan":
        return _scale(add_c, tab_entries - 2)
    c = collections.Counter()
    for k in range(2, tab_entries):
        c.update(dbl_c if k % 2 == 0 else add_c)
    return c


def _scale(counts: dict, k: int) -> collections.Counter:
    return collections.Counter({op: n * k for op, n in counts.items()})


def field_op_model(
    point_form: "str | None" = None,
    field_reduce: "str | None" = None,
    window_bits: "int | None" = None,
) -> dict:
    """Per-verify (per lane) field-op counts for each signature algorithm,
    assembled from kernel.py's structure under the ACTIVE formulation
    modes (or ``point_form``/``field_reduce``/``window_bits`` explicitly
    — the A/B comparisons the ISSUE 8/12 acceptances want stated side by
    side; explicit modes are applied process-wide for the duration of
    the call and restored after)."""
    from tpunode.verify import curve as C
    from tpunode.verify import field as Fm
    from tpunode.verify import kernel as K

    prev_f = Fm.field_modes()
    prev_wb = K.window_bits()
    try:
        if field_reduce is not None:
            Fm.set_field_modes(reduce=field_reduce)
        if window_bits is not None:
            K.set_kernel_modes(window_bits=window_bits)
        form = point_form or C.point_form()
        add_c, dbl_c, mixed_c = _point_op_counts()
        tab_entries = 1 << K.window_bits()  # 16 at 4-bit, 32 at 5-bit
        wb = K.window_bits()
        nwin = K.windows()
        halves = sum(
            1
            for name, nd in K._DEVICE_FIELDS
            if nd == 2 and name.startswith("d")
        )  # the 4 GLV half-scalar digit streams
        pow_digits = len(K._EULER_DIGITS)  # 64 4-bit windows
        assert len(K._PM2_DIGITS) == pow_digits

        pow_ladder = _pow_ladder_model(K._PM2_DIGITS)
        euler_ladder = _pow_ladder_model(K._EULER_DIGITS)
        q_table = _q_table_build_model(add_c, dbl_c)
        lambda_table = collections.Counter(
            {"mul": tab_entries}
        )  # β·X per entry

        # per window round: wb doublings + one add per half-scalar
        msm = _scale(dbl_c, nwin * wb)
        batch_inv = collections.Counter()
        if form == "affine":
            # mixed additions against the batch-normalized 2-coordinate
            # tables (ISSUE 8): one Montgomery-trick inversion per lane —
            # prefix/suffix/normalize muls counted by executing the live
            # helper, plus ONE shared Fermat ladder over the whole table.
            msm += _scale(mixed_c, nwin * halves)
            batch_inv = collections.Counter(_batch_inversion_counts())
            batch_inv += pow_ladder
        else:
            msm += _scale(add_c, nwin * halves)

        accept_ecdsa = collections.Counter({"mul": 2})  # m1, m2 checks
        on_curve = collections.Counter({"mul": 1, "sqr": 2})  # qy²=qx³+7

        base = (
            msm + q_table + batch_inv + lambda_table + accept_ecdsa
            + on_curve
        )
        ecdsa = base
        # BCH Schnorr: + jacobi(Y·Z) Euler pow (1 mul + ladder)
        schnorr = base + collections.Counter({"mul": 1}) + euler_ladder
        # BIP340: + Fermat inverse Z^(p-2) (ladder) + y = Y·Z⁻¹ (1 mul)
        bip340 = base + collections.Counter({"mul": 1}) + pow_ladder

        def flat(c: collections.Counter) -> dict:
            d = {op: int(c.get(op, 0)) for op in CountingField.ALL_OPS}
            mul_like = CountingField.OPS + CountingField.WIDE_OPS
            d["total_mul_like"] = sum(d[op] for op in mul_like)
            d["squarings"] = (
                d["sqr"] + d["sqr_t"] + d["sqr_wide"] + d["sqr_t_wide"]
            )
            d["reductions"] = (
                sum(d[op] for op in CountingField.OPS)
                + d["reduce_wide"]
                + d["reduce_wide_loose"]
            )
            return d

        return {
            "pt_add": dict(add_c),
            "pt_double": dict(dbl_c),
            "pt_add_mixed": dict(mixed_c),
            "point_form": form,
            "structure": {
                "windows": nwin,
                "window_bits": wb,
                "field_reduce": Fm.reduce_mode(),
                "half_scalars": halves,
                "table_entries": tab_entries,
                "pow_digits": pow_digits,
                "pow_ladder": K.pow_ladder_mode(),
                "select16": K.select_mode(),
                "batch_inversion": flat(batch_inv) if batch_inv else None,
            },
            "per_verify": {
                "ecdsa": flat(ecdsa),
                "schnorr": flat(schnorr),
                "bip340": flat(bip340),
            },
        }
    finally:
        Fm.set_field_modes(mul=prev_f[0], sqr=prev_f[1], reduce=prev_f[2])
        K.set_kernel_modes(window_bits=prev_wb)


# ---------------------------------------------------------------------------
# Layer 2: limb ops per field op (MACs from live pair tables, total int
# vector ops from a jaxpr walk)
# ---------------------------------------------------------------------------

_INT_OP_CLASSES = {
    "mul": "mul",
    "add": "add",
    "sub": "add",
    "and": "bitwise",
    "or": "bitwise",
    "xor": "bitwise",
    "shift_right_arithmetic": "shift",
    "shift_right_logical": "shift",
    "shift_left": "shift",
}


def _walk_jaxpr(jaxpr, counter: collections.Counter, mult: int,
                branch_mode: str = "min") -> None:
    import numpy as np

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "scan":
            _walk_jaxpr(eqn.params["jaxpr"].jaxpr, counter,
                        mult * eqn.params["length"], branch_mode)
        elif prim == "cond":
            subs = []
            for br in eqn.params["branches"]:
                c = collections.Counter()
                _walk_jaxpr(br.jaxpr, c, mult, branch_mode)
                subs.append(c)
            pick = min if branch_mode == "min" else max
            chosen = pick(subs, key=lambda c: sum(c.values()))
            counter.update(chosen)
        elif prim in ("pjit", "closed_call", "core_call", "remat", "checkpoint"):
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if inner is not None:
                _walk_jaxpr(getattr(inner, "jaxpr", inner), counter, mult,
                            branch_mode)
        elif prim == "dot_general":
            lhs, _rhs = eqn.invars[0].aval, eqn.invars[1].aval
            (lc, _rc), _ = eqn.params["dimension_numbers"]
            contract = int(np.prod([lhs.shape[d] for d in lc]))
            out = int(np.prod(eqn.outvars[0].aval.shape))
            counter["mac"] += mult * out * contract
        elif prim in _INT_OP_CLASSES:
            out = eqn.outvars[0].aval
            if np.issubdtype(out.dtype, np.integer) or np.issubdtype(
                out.dtype, np.bool_
            ):
                counter[_INT_OP_CLASSES[prim]] += mult * int(np.prod(out.shape))


def count_int_ops(fn, *args, branch_mode: str = "min") -> dict:
    """Per-LANE integer vector op counts of ``fn`` traced on ``args``
    (trailing axis = batch): jaxpr walk, scans multiplied out, conds
    resolved per ``branch_mode`` ("min" = the skip path every lax.cond
    takes on an ECDSA-only batch, "max" = the pow path)."""
    import jax

    batch = int(args[-1].shape[-1]) if hasattr(args[-1], "shape") else 1
    # Trace through a FRESH wrapper: jax caches traces on the function
    # object, so re-tracing ``fn`` after a formulation-mode flip would
    # silently return the first mode's jaxpr (measured the hard way).
    jaxpr = jax.make_jaxpr(lambda *xs: fn(*xs))(*args)
    c: collections.Counter = collections.Counter()
    _walk_jaxpr(jaxpr.jaxpr, c, 1, branch_mode)
    return {k: v / batch for k, v in sorted(c.items())}


def field_leaf_costs(batch: int = 8) -> dict:
    """Per-lane integer op costs of the live field primitives (current
    formulation modes), via the jaxpr walk."""
    import jax.numpy as jnp
    import numpy as np

    from tpunode.verify import field as F

    a = jnp.asarray(np.ones((F.NLIMBS, batch), np.int32))
    b = jnp.asarray(np.full((F.NLIMBS, batch), 2, np.int32))
    w = jnp.asarray(np.ones((2 * F.NLIMBS - 1, batch), np.int32))
    costs = {
        "mul": count_int_ops(F.mul, a, b),
        "mul_t": count_int_ops(F.mul_t, a, b),
        "sqr": count_int_ops(F.sqr, a),
        "sqr_t": count_int_ops(F.sqr_t, a),
        "mul_small_red": count_int_ops(lambda x: F.mul_small_red(x, 21), a),
        # ISSUE 12 wide-accumulator primitives: the lazy pipeline's
        # convolutions (mul-like) and carry/fold machinery (tail)
        "mul_wide": count_int_ops(F.mul_wide, a, b),
        "mul_t_wide": count_int_ops(F.mul_t_wide, a, b),
        "sqr_wide": count_int_ops(F.sqr_wide, a),
        "sqr_t_wide": count_int_ops(F.sqr_t_wide, a),
        "reduce_wide": count_int_ops(F.reduce_wide, w),
        "reduce_wide_loose": count_int_ops(F.reduce_wide_loose, w),
        "tighten": count_int_ops(F.tighten, a),
        "acc_add": count_int_ops(lambda x, y: F.acc_add(x, y), w, w),
    }
    for op in costs:
        costs[op]["total"] = sum(costs[op].values())
    return costs


def mac_model() -> dict:
    """MACs per field op from field.py's live pair tables."""
    from tpunode.verify import field as F

    mul_macs = len(F._MUL_PAIRS)  # 576
    sqr_macs = (
        len(F._SQR_PAIRS) if F.sqr_mode() == "half" else mul_macs
    )  # 300 dedicated / 576 via mul
    return {
        "mul": mul_macs,
        "mul_t": mul_macs,
        "sqr": sqr_macs,
        "sqr_t": sqr_macs,
        "mul_small_red": F.NLIMBS + F._FN,  # a*k + the 4-limb top fold
        # ISSUE 12 wide ops: a wide product is the SAME convolution as
        # its eager twin (the reduction tail it skips has no MACs);
        # the tail ops are pure carry/fold vector work.
        "mul_wide": mul_macs,
        "mul_t_wide": mul_macs,
        "sqr_wide": sqr_macs,
        "sqr_t_wide": sqr_macs,
        "reduce_wide": 0,
        "reduce_wide_loose": 0,
        "tighten": 0,
        "acc_add": 0,
        # int8 MXU packing: an 11-bit limb splits into two <=6-bit halves,
        # so each int32 MAC becomes 4 int8 MACs (lo*lo, lo*hi, hi*lo,
        # hi*hi) accumulated in the MXU's int32 accumulators.
        "int8_split_factor": 4,
    }


# ---------------------------------------------------------------------------
# Layer 3: chip model and utilization
# ---------------------------------------------------------------------------

# Datasheet-anchored numbers for TPU v5e (the part behind this box's
# tunnel).  int8 TOPS and bf16 TFLOPS are published; the clock is derived
# from the bf16 number (197e12 / (2 ops/MAC * 4 MXUs * 128 * 128) ≈
# 1.5 GHz) — int8 runs the MXUs at DOUBLE rate, so deriving from 394
# int8 TOPS without that extra factor of 2 would double the clock and
# with it every VPU bound (the published v5e clock is ~1.7 GHz; ours is
# deliberately the conservative datasheet-implied one).  The VPU int32
# peak is an ESTIMATE: 8x128 vector lanes * clock * 2-wide issue —
# utilization numbers against it are order-of-magnitude, which is all a
# "what fraction of the chip" answer needs.
CHIPS = {
    "v5e": {
        "mxu_int8_tops": 394.0,
        "bf16_tflops": 197.0,
        "clock_ghz": 197.0e12 / (2 * 4 * 128 * 128) / 1e9,
        "vpu_lanes": 8 * 128,
        "vpu_issue": 2,
        "hbm_gbps": 819.0,
    }
}

# Measured rates to evaluate (sigs/s/chip) with provenance.  The r3 rows
# are the only on-device numbers banked so far (PERF.md); cpu-jax rows
# are the tunnel-down proxy and get no chip-utilization claim.
MEASURED = {
    "pallas@32768": {"rate": 210_900.0, "provenance": "PERF.md r3 table"},
    "pallas@8192": {"rate": 94_600.0, "provenance": "PERF.md r3 table"},
    "xla@8192": {"rate": 41_100.0, "provenance": "PERF.md r3 table"},
}


# Which bare convolution each product op embeds: the difference between
# an op's leaf cost and its bare convolution's IS its carry/fold work
# (input carry rounds + the reduction tail) — the ops the ISSUE 12 lazy
# pipeline removes.  Tail ops (reduce_wide/tighten/acc_add) are pure
# carry/fold; mul_small_red's convolution part is its scale multiply.
_CONV_OF = {
    "mul": "mul_t_wide",
    "mul_t": "mul_t_wide",
    "mul_wide": "mul_t_wide",
    "mul_t_wide": "mul_t_wide",
    "sqr": "sqr_t_wide",
    "sqr_t": "sqr_t_wide",
    "sqr_wide": "sqr_t_wide",
    "sqr_t_wide": "sqr_t_wide",
}


def _carry_fold_cost(op: str, leaf: dict) -> float:
    """Per-call carry/fold vector ops of ``op``: leaf total minus the
    embedded bare convolution (multiplies + anti-diagonal accumulation),
    which laziness never changes."""
    if op in _CONV_OF:
        return leaf[op]["total"] - leaf[_CONV_OF[op]]["total"]
    if op == "mul_small_red":  # conv part = the scale/fold multiplies
        return leaf[op]["total"] - leaf[op].get("mul", 0) - leaf[op].get(
            "mac", 0
        )
    return leaf[op]["total"]  # reduce_wide / tighten / acc_add


def _per_algo_work(ops: dict, macs: dict, leaf: dict) -> dict:
    per_algo = {}
    all_ops = CountingField.ALL_OPS
    for algo, counts in ops["per_verify"].items():
        mac_total = sum(counts[op] * macs[op] for op in all_ops)
        vec_total = sum(
            counts[op] * leaf[op]["total"] for op in all_ops
        )
        vec_mul = sum(
            counts[op] * (leaf[op].get("mul", 0) + leaf[op].get("mac", 0))
            for op in all_ops
        )
        carry_fold = sum(
            counts[op] * _carry_fold_cost(op, leaf) for op in all_ops
        )
        per_algo[algo] = {
            "field_muls": counts["total_mul_like"],
            "squarings": counts["squarings"],
            "reductions": counts["reductions"],
            "int32_macs": int(mac_total),
            "int8_macs_if_packed": int(mac_total * macs["int8_split_factor"]),
            # field ops only; the MSM's selects/einsums add ~20-30% more
            # (bench-measured, PERF.md) — this is the arithmetic floor
            "vector_int_ops": int(vec_total),
            "vector_mul_ops": int(vec_mul),
            # input-carry + reduction-tail ops only (convolution
            # accumulation excluded): the rounds ISSUE 12 fuses
            "carry_fold_vector_ops": int(carry_fold),
        }
    return per_algo


def roofline(chip: str = "v5e") -> dict:
    """The full model: op counts -> per-verify work -> ideal rates ->
    utilization of the measured rates — under the ACTIVE formulation
    modes, with a projective-vs-affine comparison block (ISSUE 8)."""
    from tpunode.verify import curve as C
    from tpunode.verify import field as F
    from tpunode.verify import kernel as K

    ch = CHIPS[chip]
    ops = field_op_model()
    macs = mac_model()
    leaf = field_leaf_costs()

    per_algo = _per_algo_work(ops, macs, leaf)

    vpu_ops_s = ch["vpu_lanes"] * ch["vpu_issue"] * ch["clock_ghz"] * 1e9
    mxu_macs_s = ch["mxu_int8_tops"] * 1e12 / 2  # TOPS counts mul+add
    bounds = {}
    for algo, w in per_algo.items():
        bounds[algo] = {
            # every op on the VPU (the shift-add formulation's bound)
            "vpu_bound_sigs_s": vpu_ops_s / w["vector_int_ops"],
            # MACs on the MXU at int8, carry/fold rounds still on the VPU
            # (the dot_general formulation's bound; VPU part dominates)
            "mxu_bound_sigs_s": 1.0 / (
                w["int8_macs_if_packed"] / mxu_macs_s
                + (w["vector_int_ops"] - w["vector_mul_ops"]) / vpu_ops_s
            ),
        }

    # Projective-vs-affine A/B at the arithmetic floor (ECDSA headline
    # workload): the affine form trades one batch inversion (one Fermat
    # ladder + ~67 muls per lane) for 132 cheaper window additions and a
    # third less select traffic — the FIELD-OP floor moves one way, the
    # non-arithmetic overhead the other; the measured step-time delta
    # (PERF.md) is the decider.
    form_compare = {}
    for form in C.POINT_FORMS:
        w = _per_algo_work(field_op_model(form), macs, leaf)["ecdsa"]
        form_compare[form] = {
            "field_muls": w["field_muls"],
            "vector_int_ops": w["vector_int_ops"],
            "vpu_bound_sigs_s": round(vpu_ops_s / w["vector_int_ops"]),
        }

    # Lazy-reduction x window-width A/B at the arithmetic floor (ISSUE
    # 12): the lazy model must remove a MEASURABLE share of the
    # carry/fold vector ops (the acceptance pin is >= 25% for the ECDSA
    # per-verify total, tested in test_benchmarks), and the 5-bit
    # windows cut rounds at the cost of bigger tables.
    reduce_compare = {}
    for red in ("eager", "lazy"):
        for wbits in K.WINDOW_BITS_MODES:
            w = _per_algo_work(
                field_op_model(field_reduce=red, window_bits=wbits),
                macs,
                leaf,
            )["ecdsa"]
            reduce_compare[f"{red}@w{wbits}"] = {
                "field_muls": w["field_muls"],
                "reductions": w["reductions"],
                "vector_int_ops": w["vector_int_ops"],
                "carry_fold_vector_ops": w["carry_fold_vector_ops"],
                "vpu_bound_sigs_s": round(vpu_ops_s / w["vector_int_ops"]),
            }

    # Bytes per lane over the PCIe/HBM boundary (device inputs + verdict):
    # 4 digit streams x windows() + 4 limb arrays + masks.
    in_bytes = 4 * K.windows() * 4 + 4 * F.NLIMBS * 4 + 6 * 1 + 4
    util = {}
    for label, m in MEASURED.items():
        algo = "ecdsa"  # the headline workload is ECDSA-only
        util[label] = {
            "rate": m["rate"],
            "provenance": m["provenance"],
            "vpu_utilization": m["rate"] / bounds[algo]["vpu_bound_sigs_s"],
            "of_mxu_bound": m["rate"] / bounds[algo]["mxu_bound_sigs_s"],
            "hbm_gbps_used": m["rate"] * in_bytes / 1e9,
        }

    return {
        "chip": chip,
        "chip_model": ch,
        "field_modes": {
            "mul": F.mul_mode(),
            "sqr": F.sqr_mode(),
            "reduce": F.reduce_mode(),
        },
        "kernel_modes": {
            "point_form": C.point_form(),
            "select16": K.select_mode(),
            "pow_ladder": K.pow_ladder_mode(),
            "window_bits": K.window_bits(),
        },
        "point_form_compare": form_compare,
        "reduce_window_compare": reduce_compare,
        "op_model": ops,
        "mac_model": macs,
        "leaf_costs": {k: {kk: round(vv, 1) for kk, vv in v.items()}
                       for k, v in leaf.items()},
        "per_verify": per_algo,
        "ideal_sigs_per_s": {
            k: {kk: round(vv) for kk, vv in v.items()}
            for k, v in bounds.items()
        },
        "device_bytes_per_verify": in_bytes,
        "utilization": {
            k: {kk: (round(vv, 4) if isinstance(vv, float) else vv)
                for kk, vv in v.items()}
            for k, v in util.items()
        },
    }


def _markdown(r: dict) -> str:
    """The PERF.md tables."""
    lines = []
    pv = r["per_verify"]
    lines.append("| algorithm | field muls | (of which sqr) | int32 MACs "
                 "| vector int ops (field only) |")
    lines.append("|---|---|---|---|---|")
    for algo in ("ecdsa", "schnorr", "bip340"):
        w = pv[algo]
        lines.append(
            f"| {algo} | {w['field_muls']} | {w['squarings']} "
            f"| {w['int32_macs']:,} | {w['vector_int_ops']:,} |"
        )
    lines.append("")
    lines.append("| measured program | sigs/s | VPU utilization "
                 "| of MXU-mapped bound | HBM GB/s (host I/O) |")
    lines.append("|---|---|---|---|---|")
    for label, u in r["utilization"].items():
        lines.append(
            f"| {label} | {u['rate']:,.0f} | {u['vpu_utilization']:.1%} "
            f"| {u['of_mxu_bound']:.1%} | {u['hbm_gbps_used']:.3f} |"
        )
    ideal = r["ideal_sigs_per_s"]["ecdsa"]
    lines.append("")
    lines.append(
        f"Ideal ECDSA rates on one {r['chip']}: "
        f"**{ideal['vpu_bound_sigs_s']:,} sigs/s** all-VPU (shift-add), "
        f"**{ideal['mxu_bound_sigs_s']:,} sigs/s** with the limb products "
        f"on the MXU at int8 (dot_general + packing; carry/fold stays on "
        f"the VPU and dominates that bound)."
    )
    lines.append("")
    lines.append("| point form (ecdsa) | field muls | vector int ops "
                 "| all-VPU bound (sigs/s) |")
    lines.append("|---|---|---|---|")
    for form, w in r["point_form_compare"].items():
        lines.append(
            f"| {form} | {w['field_muls']} | {w['vector_int_ops']:,} "
            f"| {w['vpu_bound_sigs_s']:,} |"
        )
    lines.append("")
    lines.append("| reduce@width (ecdsa) | field muls | reductions "
                 "| carry/fold vec ops | vector int ops "
                 "| all-VPU bound (sigs/s) |")
    lines.append("|---|---|---|---|---|---|")
    for key, w in r["reduce_window_compare"].items():
        lines.append(
            f"| {key} | {w['field_muls']} | {w['reductions']} "
            f"| {w['carry_fold_vector_ops']:,} | {w['vector_int_ops']:,} "
            f"| {w['vpu_bound_sigs_s']:,} |"
        )
    return "\n".join(lines)


def main() -> None:
    chip = "v5e"
    for a in sys.argv[1:]:
        if a.startswith("--chip="):
            chip = a.split("=", 1)[1]
    r = roofline(chip)
    if "--markdown" in sys.argv:
        print(_markdown(r))
    else:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
