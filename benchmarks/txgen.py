"""Deterministic generation of realistic signed-transaction workloads.

Builds P2PKH-spending transactions signed with the CPU oracle and packs
them into consensus-valid regtest blocks (headers connect under
tpunode.headers.connect_blocks: correct prev-links, merkle roots, and
regtest PoW by nonce grinding against the trivial target).  Everything is
seeded and cached on disk, so benchmark runs are reproducible and the
pure-Python signing cost is paid once.

The reference has no benchmark data generator (SURVEY.md §6: no benchmarks
anywhere); this is the stand-in for its real-world inputs (mainnet block
800000, IBD replay, mempool firehose) in a zero-egress environment —
shaped like the real thing, labelled synthetic.
"""

from __future__ import annotations

import hashlib
import os
import random
import time
from typing import Optional

from tpunode.headers import genesis_node
from tpunode.util import bits_to_target
from tpunode.params import Network
from tpunode.sighash import (
    SIGHASH_ALL,
    bip143_sighash,
    bip341_sighash,
    legacy_sighash,
    tapleaf_hash,
)
from tpunode.txverify import _hash160, _p2pkh_script_code
from tpunode.util import Reader, double_sha256
from tpunode.verify.ecdsa_cpu import (
    CURVE_N,
    GENERATOR,
    point_mul,
    sign,
    sign_bip340,
    sign_schnorr,
)
from tpunode.wire import (
    Block,
    BlockHeader,
    OutPoint,
    Tx,
    TxIn,
    TxOut,
    build_merkle_root,
)

__all__ = [
    "gen_signed_txs",
    "gen_mixed_txs",
    "gen_chain",
    "synth_amount",
    "synth_prevout",
    "cache_path",
]

_CACHE_DIR = os.path.join(os.path.dirname(__file__), "data")


def cache_path(name: str) -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    return os.path.join(_CACHE_DIR, name)


def _der(r: int, s: int) -> bytes:
    def enc_int(v: int) -> bytes:
        b = v.to_bytes((v.bit_length() + 8) // 8 or 1, "big")
        return b"\x02" + bytes([len(b)]) + b

    body = enc_int(r) + enc_int(s)
    return b"\x30" + bytes([len(body)]) + body


def _pub_blob(pub) -> bytes:
    return bytes([2 + (pub.y & 1)]) + pub.x.to_bytes(32, "big")


def gen_signed_txs(
    count: int,
    inputs_per_tx: int = 2,
    seed: int = 0xB10C,
    invalid_every: int = 0,
    segwit_every: int = 0,
) -> list[Tx]:
    """``count`` P2PKH-spending txs, each with ``inputs_per_tx`` signed
    inputs.  ``invalid_every`` > 0 corrupts every Nth tx's first signature
    (to keep verifiers honest).  ``segwit_every`` > 0 makes every Nth tx a
    P2WPKH spend (BIP143 digest) of the PREVIOUS tx's output 0, so packed
    into one block the prevout amount is resolvable intra-block — the
    channel node._verify_txs wires into extract_sig_items."""
    rng = random.Random(seed)
    priv = rng.getrandbits(256) % CURVE_N or 1
    pub = point_mul(priv, GENERATOR)
    blob = _pub_blob(pub)
    script_code = _p2pkh_script_code(blob)
    out_script = script_code  # pay back to the same key
    txs: list[Tx] = []
    for t in range(count):
        if segwit_every and t % segwit_every == segwit_every - 1 and txs:
            # P2WPKH: spend previous tx's output 0; witness [sig, pubkey]
            prev = txs[-1]
            amount = prev.outputs[0].value
            inputs = (TxIn(OutPoint(prev.txid, 0), b"", 0xFFFFFFFF),)
            outputs = (TxOut(50_000 + t, out_script),)
            unsigned = Tx(2, inputs, outputs, 0)
            z = bip143_sighash(unsigned, 0, script_code, amount, SIGHASH_ALL)
            r, s = sign(priv, z, rng.getrandbits(256) % CURVE_N or 1)
            if invalid_every and t % invalid_every == invalid_every - 1:
                s = (s + 1) % CURVE_N or 1
            sig_blob = _der(r, s) + bytes([SIGHASH_ALL])
            txs.append(
                Tx(2, inputs, outputs, 0, witnesses=((sig_blob, blob),))
            )
            continue
        inputs = tuple(
            TxIn(OutPoint(rng.randbytes(32), i), b"", 0xFFFFFFFF)
            for i in range(inputs_per_tx)
        )
        outputs = (TxOut(50_000 + t, out_script),)
        unsigned = Tx(1, inputs, outputs, 0)
        signed = []
        for i in range(inputs_per_tx):
            z = legacy_sighash(unsigned, i, script_code, SIGHASH_ALL)
            r, s = sign(priv, z, rng.getrandbits(256) % CURVE_N or 1)
            if invalid_every and t % invalid_every == invalid_every - 1 and i == 0:
                s = (s + 1) % CURVE_N or 1
            sig_blob = _der(r, s) + bytes([SIGHASH_ALL])
            script_sig = (
                bytes([len(sig_blob)]) + sig_blob + bytes([len(blob)]) + blob
            )
            signed.append(TxIn(inputs[i].prevout, script_sig, 0xFFFFFFFF))
        txs.append(Tx(1, tuple(signed), outputs, 0))
    return txs


def synth_amount(txid: bytes, vout: int) -> int:
    """Deterministic synthetic prevout amount, derived from the outpoint
    itself — so benchmark prevout lookups need no side table: generation
    signs BIP143 inputs against ``synth_amount(prevout)`` and the bench
    passes this function as ``NodeConfig.prevout_lookup``."""
    return 10_000 + (int.from_bytes(txid[:6], "little") ^ vout) % 5_000_000


def _synth_is_p2tr(txid: bytes, vout: int) -> bool:
    """Deterministic script-type coin flip for the synthetic UTXO set:
    ~1/4 of outpoints are taproot-typed."""
    return ((txid[1] ^ vout) & 0x03) == 0


def _synth_is_p2pk(txid: bytes, vout: int) -> bool:
    """~1/8 of outpoints are bare-P2PK-typed (disjoint from the taproot
    set: low two bits 0b10)."""
    return ((txid[1] ^ vout) & 0x07) == 2


def _synth_tap_priv(txid: bytes, vout: int) -> int:
    return (
        int.from_bytes(
            double_sha256(b"tapkey" + txid + vout.to_bytes(4, "little")), "big"
        )
        % CURVE_N
        or 1
    )


_TAP_SCRIPT_CACHE: dict[tuple[bytes, int], bytes] = {}


def synth_prevout(txid: bytes, vout: int):
    """Extended deterministic prevout oracle: (amount, scriptPubKey).

    Taproot-typed outpoints (``_synth_is_p2tr``) get a P2TR script whose
    output key is derivable from the outpoint (``_synth_tap_priv``), so
    generation can sign keypath spends and verification can detect them —
    all without a side table.  Pass as ``NodeConfig.prevout_lookup``; the
    node accepts both the plain-amount and the (amount, script) forms."""
    amount = synth_amount(txid, vout)
    if _synth_is_p2tr(txid, vout):
        key = (txid, vout)
        script = _TAP_SCRIPT_CACHE.get(key)
        if script is None:
            P = point_mul(_synth_tap_priv(txid, vout), GENERATOR)
            script = b"\x51\x20" + P.x.to_bytes(32, "big")
            if len(_TAP_SCRIPT_CACHE) < 1 << 16:
                _TAP_SCRIPT_CACHE[key] = script
    elif _synth_is_p2pk(txid, vout):
        key = (txid, ~vout)
        script = _TAP_SCRIPT_CACHE.get(key)
        if script is None:
            P = point_mul(_synth_tap_priv(txid, vout), GENERATOR)
            script = b"\x21" + _pub_blob(P) + b"\xac"
            if len(_TAP_SCRIPT_CACHE) < 1 << 16:
                _TAP_SCRIPT_CACHE[key] = script
    else:
        script = (
            b"\x76\xa9\x14" + double_sha256(b"pkh" + txid)[:20] + b"\x88\xac"
        )
    return amount, script


def _push(b: bytes) -> bytes:
    """Minimal script push of ``b``."""
    if len(b) <= 75:
        return bytes([len(b)]) + b
    if len(b) <= 255:
        return b"\x4c" + bytes([len(b)]) + b
    return b"\x4d" + len(b).to_bytes(2, "little") + b


def _msig_script(m: int, key_blobs: list[bytes]) -> bytes:
    """Bare multisig template: OP_m <key>*n OP_n OP_CHECKMULTISIG."""
    return (
        bytes([0x50 + m])
        + b"".join(bytes([len(k)]) + k for k in key_blobs)
        + bytes([0x50 + len(key_blobs), 0xAE])
    )


# Realistic mainnet-shaped script-type mix (cumulative weights): multisig-
# heavy per VERDICT r3 item 3, taproot keypath per r4 item 3, with a slice
# of genuinely unsupported inputs (taproot SCRIPT-path spends) so the
# coverage metric measures something.
_MIX = [
    (0.15, "p2pkh"),
    (0.18, "p2pk"),
    (0.38, "p2wpkh"),
    (0.48, "p2sh-p2wpkh"),
    (0.52, "p2wsh-single"),
    (0.62, "p2sh-msig"),
    (0.73, "p2wsh-msig"),
    (0.89, "p2tr"),
    (0.95, "p2tr-script"),
    (1.01, "unsupported"),
]

# Taproot-dominated variant (modern BTC mempool shape) for the
# coverage-on-taproot-heavy acceptance test (VERDICT r4 item 3).
_MIX_TAPROOT_HEAVY = [
    (0.10, "p2pkh"),
    (0.20, "p2wpkh"),
    (0.96, "p2tr"),
    (1.01, "unsupported"),
]


def gen_mixed_txs(
    count: int,
    seed: int = 0x1213,
    invalid_every: int = 0,
    inputs_per_tx: int = 2,
    schnorr_every: int = 0,
    taproot: bool = True,
    mix: Optional[list] = None,
) -> list[Tx]:
    """``count`` txs drawn from the realistic script-type mix (_MIX): P2PKH,
    P2WPKH, P2SH-P2WPKH, 2-of-3 P2SH multisig, 2-of-3 P2WSH multisig,
    taproot keypath (~20%), plus ~5% unsupported (taproot script-path
    shapes).  One template per tx (mixed witness presence within a tx
    complicates serialization for no benchmark value).  BIP143 inputs are
    signed against ``synth_amount(prevout)``; taproot inputs against the
    extended ``synth_prevout`` oracle — pass ``synth_prevout`` as the
    prevout lookup when verifying.  ``invalid_every`` corrupts every Nth
    tx's first signature.  ``schnorr_every`` > 0 makes every Nth tx a
    BCH-Schnorr-signed P2PKH spend (65-byte sig, ALL|FORKID hashtype —
    verify with ``bch=True``).  ``taproot=False`` (BCH networks: no
    taproot there) remaps p2tr rolls to p2wpkh.  ``mix`` overrides the
    weight table (e.g. ``_MIX_TAPROOT_HEAVY``)."""
    rng = random.Random(seed)
    mix = mix if mix is not None else _MIX
    privs = [rng.getrandbits(256) % CURVE_N or 1 for _ in range(3)]
    pubs = [point_mul(p, GENERATOR) for p in privs]
    blobs = [_pub_blob(p) for p in pubs]
    redeem = _msig_script(2, blobs)  # shared 2-of-3 template
    wscript = b"\x21" + blobs[0] + b"\xac"  # shared P2WSH single-key script
    out_script = _p2pkh_script_code(blobs[0])

    def outpoint(want: str = "other") -> OutPoint:
        """Random outpoint, rejection-sampled to the wanted synthetic
        script type ("p2tr" | "p2pk" | "other")."""
        while True:
            po = OutPoint(rng.randbytes(32), rng.randrange(4))
            kind_of = (
                "p2tr" if _synth_is_p2tr(po.txid, po.index)
                else "p2pk" if _synth_is_p2pk(po.txid, po.index)
                else "other"
            )
            if kind_of == want:
                return po

    txs: list[Tx] = []
    for t in range(count):
        roll = rng.random()
        kind = next(k for w, k in mix if roll < w)
        if kind in ("p2tr", "p2tr-script") and not taproot:
            kind = "p2wpkh"
        if schnorr_every and t % schnorr_every == schnorr_every - 1:
            kind = "p2pkh-schnorr"
        corrupt = invalid_every and t % invalid_every == invalid_every - 1
        # taproot/p2pk kinds pin the synthetic prevout type; the rest
        # avoid those outpoint types so the oracle's script can't
        # reclassify them
        want = (
            "p2tr" if kind in ("p2tr", "p2tr-script", "unsupported")
            else "p2pk" if kind == "p2pk"
            else "other"
        )
        prevouts = tuple(outpoint(want) for _ in range(inputs_per_tx))
        outputs = (TxOut(50_000 + t, out_script),)
        version = 2 if kind != "p2pkh" else 1
        inputs = tuple(TxIn(po, b"", 0xFFFFFFFF) for po in prevouts)
        if kind == "p2sh-p2wpkh":
            # scriptSig carries the v0 keyhash redeem program
            redeem_prog = b"\x00\x14" + _hash160(blobs[0])
            inputs = tuple(
                TxIn(po, _push(redeem_prog), 0xFFFFFFFF) for po in prevouts
            )
        elif kind == "p2sh-p2wsh":  # pragma: no cover — not in _MIX yet
            prog = b"\x00\x20" + hashlib.sha256(redeem).digest()
            inputs = tuple(TxIn(po, _push(prog), 0xFFFFFFFF) for po in prevouts)
        unsigned = Tx(version, inputs, outputs, 0)
        if kind == "unsupported":
            # taproot SCRIPT-path shape: [stack-elem, tapscript, control] —
            # genuinely unsupported (this engine doesn't run tapscript)
            txs.append(
                Tx(version, inputs, outputs, 0,
                   witnesses=tuple(
                       (b"\x01", b"\x51", b"\xc0" + rng.randbytes(32))
                       for _ in prevouts
                   ))
            )
            continue
        if kind == "p2pk":
            # bare P2PK: scriptSig = <sig>, key in the (oracle) prevout
            # script; legacy sighash with the prevout script as code
            signed_ins = []
            for i, po in enumerate(prevouts):
                pscript = synth_prevout(po.txid, po.index)[1]
                z = legacy_sighash(unsigned, i, pscript, SIGHASH_ALL)
                r, s = sign(
                    _synth_tap_priv(po.txid, po.index), z,
                    rng.getrandbits(256) % CURVE_N or 1,
                )
                if corrupt and i == 0:
                    s = (s + 1) % CURVE_N or 1
                sig_blob = _der(r, s) + bytes([SIGHASH_ALL])
                signed_ins.append(TxIn(po, _push(sig_blob), 0xFFFFFFFF))
            txs.append(Tx(version, tuple(signed_ins), outputs, 0))
            continue
        if kind in ("p2tr", "p2tr-script"):
            amounts = [synth_amount(po.txid, po.index) for po in prevouts]
            scripts = [synth_prevout(po.txid, po.index)[1] for po in prevouts]
            wits = []
            for i, po in enumerate(prevouts):
                if kind == "p2tr-script":
                    # script path: the canonical single-key tapscript,
                    # leaf key derived from the outpoint (distinct from
                    # the output key), minimal control block
                    leaf_priv = _synth_tap_priv(po.txid, po.index + 1000)
                    LP = point_mul(leaf_priv, GENERATOR)
                    leaf_script = b"\x20" + LP.x.to_bytes(32, "big") + b"\xac"
                    control = b"\xc0" + scripts[i][2:34]
                    digest = bip341_sighash(
                        unsigned, i, amounts, scripts, 0x00,
                        leaf_hash=tapleaf_hash(leaf_script),
                    )
                    r, s = sign_bip340(
                        leaf_priv, digest, rng.getrandbits(256) % CURVE_N or 1
                    )
                    if corrupt and i == 0:
                        s = (s + 1) % CURVE_N or 1
                    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
                    wits.append((sig, leaf_script, control))
                    continue
                digest = bip341_sighash(unsigned, i, amounts, scripts, 0x00)
                r, s = sign_bip340(
                    _synth_tap_priv(po.txid, po.index),
                    digest,
                    rng.getrandbits(256) % CURVE_N or 1,
                )
                if corrupt and i == 0:
                    s = (s + 1) % CURVE_N or 1
                wits.append((r.to_bytes(32, "big") + s.to_bytes(32, "big"),))
            txs.append(
                Tx(version, inputs, outputs, 0, witnesses=tuple(wits))
            )
            continue
        signed_ins: list[TxIn] = []
        wit_stacks: list[tuple[bytes, ...]] = []
        for i, po in enumerate(prevouts):
            amount = synth_amount(po.txid, po.index)
            if kind == "p2pkh-schnorr":
                # BCH Schnorr over the FORKID (BIP143-style) digest
                ht = SIGHASH_ALL | 0x40  # SIGHASH_FORKID
                z = bip143_sighash(unsigned, i, out_script, amount, ht)
                r, s = sign_schnorr(
                    privs[0], z, rng.getrandbits(256) % CURVE_N or 1
                )
                if corrupt and i == 0:
                    s = (s + 1) % CURVE_N
                sig_blob = (
                    r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([ht])
                )
                signed_ins.append(
                    TxIn(po, _push(sig_blob) + _push(blobs[0]), 0xFFFFFFFF)
                )
                wit_stacks.append(())
                continue
            if kind == "p2pkh":
                z = legacy_sighash(unsigned, i, out_script, SIGHASH_ALL)
            elif kind == "p2sh-msig":
                z = legacy_sighash(unsigned, i, redeem, SIGHASH_ALL)
            elif kind == "p2wsh-msig":
                z = bip143_sighash(unsigned, i, redeem, amount, SIGHASH_ALL)
            elif kind == "p2wsh-single":
                # witness script <key> OP_CHECKSIG is the script_code
                z = bip143_sighash(unsigned, i, wscript, amount, SIGHASH_ALL)
            else:  # p2wpkh / p2sh-p2wpkh
                z = bip143_sighash(unsigned, i, out_script, amount, SIGHASH_ALL)
            if kind in ("p2sh-msig", "p2wsh-msig"):
                # 2-of-3: a random ordered pair of keys signs (the consensus
                # walk must handle skipped keys, so don't always use 0,1)
                ki = sorted(rng.sample(range(3), 2))
                sig_blobs = []
                for which, k in enumerate(ki):
                    r, s = sign(privs[k], z, rng.getrandbits(256) % CURVE_N or 1)
                    if corrupt and i == 0 and which == 0:
                        s = (s + 1) % CURVE_N or 1
                    sig_blobs.append(_der(r, s) + bytes([SIGHASH_ALL]))
                if kind == "p2sh-msig":
                    script_sig = (
                        b"\x00"
                        + b"".join(_push(sb) for sb in sig_blobs)
                        + _push(redeem)
                    )
                    signed_ins.append(TxIn(po, script_sig, 0xFFFFFFFF))
                    wit_stacks.append(())
                else:
                    signed_ins.append(TxIn(po, b"", 0xFFFFFFFF))
                    wit_stacks.append((b"", *sig_blobs, redeem))
            else:
                r, s = sign(privs[0], z, rng.getrandbits(256) % CURVE_N or 1)
                if corrupt and i == 0:
                    s = (s + 1) % CURVE_N or 1
                sig_blob = _der(r, s) + bytes([SIGHASH_ALL])
                if kind == "p2pkh":
                    signed_ins.append(
                        TxIn(po, _push(sig_blob) + _push(blobs[0]), 0xFFFFFFFF)
                    )
                    wit_stacks.append(())
                elif kind == "p2wsh-single":
                    signed_ins.append(TxIn(po, b"", 0xFFFFFFFF))
                    wit_stacks.append((sig_blob, wscript))
                else:
                    signed_ins.append(
                        TxIn(po, inputs[i].script, 0xFFFFFFFF)
                    )
                    wit_stacks.append((sig_blob, blobs[0]))
        has_wit = any(wit_stacks)
        txs.append(
            Tx(
                version,
                tuple(signed_ins),
                outputs,
                0,
                witnesses=tuple(wit_stacks) if has_wit else (),
            )
        )
    return txs


def _coinbase(height: int) -> Tx:
    sig = bytes([4]) + height.to_bytes(4, "little")
    return Tx(
        1,
        (TxIn(OutPoint(b"\x00" * 32, 0xFFFFFFFF), sig, 0xFFFFFFFF),),
        (TxOut(50 * 100_000_000, b"\x51"),),
        0,
    )


def gen_chain(
    net: Network,
    n_blocks: int,
    txs_per_block: int,
    inputs_per_tx: int = 2,
    seed: int = 0x1BD,
    cache: Optional[str] = None,
    segwit_every: int = 0,
    mix: bool = False,
) -> list[Block]:
    """A consensus-valid chain of ``n_blocks`` regtest blocks on top of the
    genesis, each carrying signed txs — all-P2PKH by default, the realistic
    script-type mix (``gen_mixed_txs``; resolve amounts via ``synth_amount``)
    when ``mix=True``.  Cached to ``cache`` (under benchmarks/data) when
    given.  The on-disk name embeds every workload parameter (net magic,
    block/tx counts, inputs_per_tx, seed) so changing any of them can never
    silently reuse a stale workload, and the load path re-verifies the
    block count byte-for-byte."""
    if mix and segwit_every:
        raise ValueError("mix and segwit_every are mutually exclusive")
    if segwit_every:
        # each segwit tx spends its immediate predecessor, so both must land
        # in the same block for the intra-block amount map to resolve —
        # otherwise BIP143 coverage silently drops to "unsupported"
        for t in range(segwit_every - 1, n_blocks * txs_per_block, segwit_every):
            if t % txs_per_block == 0:
                raise ValueError(
                    f"segwit tx {t} would start a block and spend across the "
                    f"boundary: choose segwit_every/txs_per_block so no "
                    f"segwit index is a multiple of txs_per_block"
                )
    if cache is not None:
        key = (
            f"{net.magic:08x}-{n_blocks}x{txs_per_block}"
            f"-i{inputs_per_tx}-s{seed:x}"
            + (f"-w{segwit_every}" if segwit_every else "")
            # v4: taproot + tapscript + p2pk + p2wsh-single in the mix (r5) — the
            # key must change with the workload content or a stale cache survives
            + (("-mixs4" if net.bch else "-mix4") if mix else "")
        )
        cache = f"{os.path.splitext(cache)[0]}-{key}.bin"
        path = cache_path(cache)
        if os.path.exists(path):
            data = open(path, "rb").read()
            try:
                r = Reader(data)
                blocks = [Block.deserialize(r) for _ in range(n_blocks)]
                if r.remaining() == 0:
                    return blocks
            except Exception:
                pass  # short/corrupt cache — regenerate below

    gen = genesis_node(net)
    target = bits_to_target(net.genesis.bits)
    prev = gen.header.hash
    t0 = net.genesis.timestamp
    if mix:
        all_txs = gen_mixed_txs(
            n_blocks * txs_per_block,
            seed=seed,
            inputs_per_tx=inputs_per_tx,
            # BCH networks: every 4th tx Schnorr-signed (the realistic
            # post-2019 mix is Schnorr-heavy), and no taproot (BCH never
            # activated it); verify with bch=True
            schnorr_every=4 if net.bch else 0,
            taproot=not net.bch,
        )
    else:
        all_txs = gen_signed_txs(
            n_blocks * txs_per_block,
            inputs_per_tx=inputs_per_tx,
            seed=seed,
            segwit_every=segwit_every,
        )
    blocks = []
    for h in range(n_blocks):
        txs = [_coinbase(h + 1)] + all_txs[h * txs_per_block : (h + 1) * txs_per_block]
        merkle = build_merkle_root([t.txid for t in txs])
        nonce = 0
        while True:
            hdr = BlockHeader(
                version=0x20000000,
                prev=prev,
                merkle=merkle,
                timestamp=t0 + 600 * (h + 1),
                bits=net.genesis.bits,
                nonce=nonce,
            )
            if int.from_bytes(hdr.hash, "little") <= target:
                break
            nonce += 1
        blocks.append(Block(hdr, tuple(txs)))
        prev = hdr.hash
    if cache is not None:
        # atomic: a killed run must not leave a truncated cache behind
        path = cache_path(cache)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            for b in blocks:
                f.write(b.serialize())
        os.replace(tmp, path)
    return blocks
