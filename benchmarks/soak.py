"""Churn soak: a node under mempool load with periodic remote deaths.

    SOAK_SECONDS=300 python -m benchmarks.soak

Runs N seconds over the real TCP transport: wire-speaking remotes stream
mixed-script tx gossip (incl. multisig + BCH Schnorr); every ~10s the live
remote sockets are killed — the node must publish PeerDisconnected and
re-dial (reference elasticity: kill freely, repopulate from the pool,
PeerMgr.hs:606-625) — while TxVerdict flow continues.  Exit asserts: >=10
churn cycles survived, re-dials happened, verdicts never stalled, and
asyncio task count / RSS end where they started (no leaks).  Round-4
measurements: 300s — 30 kills, 79k verdicts, tasks 15->15; 1200s — 117
kills/reconnects, 301k verdicts / 743k sigs, tasks 16->16, RSS flat at
167MB.
"""

import asyncio
import contextlib
import gc
import os
import random
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

from tests.fakenet import mock_peer_react
from tests.fixtures import all_blocks
from benchmarks.txgen import gen_mixed_txs, synth_prevout
from tpunode import Node, NodeConfig, Publisher, TxVerdict
from tpunode.chain import ChainSynced
from tpunode.params import BCH_REGTEST as NET, NODE_NETWORK
from tpunode.peer import PeerConnected, PeerDisconnected
from tpunode.store import MemoryKV
from tpunode.verify.engine import VerifyConfig
from tpunode.wire import MsgTx, NetworkAddress, MsgVersion, encode_message, \
    decode_message, decode_message_header, HEADER_SIZE

DURATION = float(os.environ.get("SOAK_SECONDS", 300))
BLOCKS = all_blocks()
TXS = gen_mixed_txs(64, seed=0x50AC, schnorr_every=4, invalid_every=9)
ENCODED = [encode_message(NET, MsgTx(t)) for t in TXS]


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024
    return 0.0


async def remote(reader, writer, writers):
    writers.append(writer)
    rng = random.Random()
    try:
        ver = MsgVersion(
            version=70012, services=NODE_NETWORK, timestamp=int(time.time()),
            addr_recv=NetworkAddress.from_host_port("127.0.0.1", 0),
            addr_from=NetworkAddress.from_host_port(
                "127.0.0.1", 0, services=NODE_NETWORK),
            nonce=rng.getrandbits(64), user_agent=b"/soak/",
            start_height=len(BLOCKS), relay=True)
        writer.write(encode_message(NET, ver))
        await writer.drain()

        async def pump():
            i = rng.randrange(64)
            while True:
                writer.write(ENCODED[i % len(ENCODED)])
                i += 1
                if i % 16 == 0:
                    await writer.drain()
                    await asyncio.sleep(0.05)

        pumper = asyncio.ensure_future(  # asyncsan: disable=raw-spawn (soak harness task, cancelled in finally)
            pump()
        )
        try:
            while True:
                hdr_raw = await reader.readexactly(HEADER_SIZE)
                hdr = decode_message_header(NET, hdr_raw)
                payload = await reader.readexactly(hdr.length) if hdr.length else b""
                msg = decode_message(NET, hdr, payload)
                for reply in mock_peer_react(NET, BLOCKS, msg):
                    writer.write(encode_message(NET, reply))
                await writer.drain()
        finally:
            pumper.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await pumper
    except (asyncio.IncompleteReadError, ConnectionError):
        pass
    finally:
        with contextlib.suppress(Exception):
            writer.close()


async def main():
    writers: list = []
    server = await asyncio.start_server(
        lambda r, w: remote(r, w, writers), "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    pub = Publisher(name="soak", maxsize=None)  # exact counts: bench bus must be lossless
    cfg = NodeConfig(
        net=NET, store=MemoryKV(), pub=pub,
        peers=[f"127.0.0.1:{port}"] * 1 + [f"127.0.0.1:{port}"],
        max_peers=3, discover=False,
        verify=VerifyConfig(backend="cpu", max_wait=0.01, warmup=False),
        prevout_lookup=synth_prevout,
    )
    stats = {"verdicts": 0, "sigs": 0, "connects": 0, "disconnects": 0,
             "kills": 0}
    t_end = time.monotonic() + DURATION

    async def consume(events):
        while True:
            ev = await events.receive()
            if isinstance(ev, TxVerdict):
                stats["verdicts"] += 1
                stats["sigs"] += len(ev.verdicts)
            elif isinstance(ev, PeerConnected):
                stats["connects"] += 1
            elif isinstance(ev, PeerDisconnected):
                stats["disconnects"] += 1

    async with pub.subscription() as events:
        async with Node(cfg) as node:
            consumer = asyncio.ensure_future(  # asyncsan: disable=raw-spawn (soak harness task, cancelled on teardown)
                consume(events)
            )
            await asyncio.sleep(5)
            gc.collect()
            base_tasks = len(asyncio.all_tasks())
            base_rss = rss_mb()
            last_report = time.monotonic()
            last_verdicts = 0
            while time.monotonic() < t_end:
                await asyncio.sleep(10)
                # churn: kill every live remote socket; node must recover
                victims = [w for w in writers if not w.is_closing()]
                for w in victims[:2]:
                    w.close()
                    stats["kills"] += 1
                if time.monotonic() - last_report > 30:
                    dv = stats["verdicts"] - last_verdicts
                    assert dv > 0, f"verdict flow stalled: {stats}"
                    last_verdicts = stats["verdicts"]
                    last_report = time.monotonic()
                    gc.collect()
                    print(f"[soak] t={DURATION - (t_end - time.monotonic()):.0f}s "
                          f"verdicts={stats['verdicts']} sigs={stats['sigs']} "
                          f"kills={stats['kills']} "
                          f"conn={stats['connects']}/{stats['disconnects']} "
                          f"tasks={len(asyncio.all_tasks())} rss={rss_mb():.0f}MB",
                          flush=True)
            consumer.cancel()
            gc.collect()
            end_tasks = len(asyncio.all_tasks())
            end_rss = rss_mb()
    server.close()
    print(f"[soak] done: {stats}")
    print(f"[soak] tasks {base_tasks} -> {end_tasks}, rss {base_rss:.0f} -> {end_rss:.0f} MB")
    min_cycles = max(2, int(DURATION // 30))
    assert stats["kills"] >= min_cycles, stats
    assert stats["disconnects"] >= min_cycles - 1, stats
    assert stats["connects"] >= stats["disconnects"], stats  # re-dials happened
    assert stats["verdicts"] > 100, stats
    assert end_tasks <= base_tasks + 8, (base_tasks, end_tasks)  # no task leak
    assert end_rss <= base_rss + 80, (base_rss, end_rss)  # no unbounded growth
    print("[soak] PASS")


if __name__ == "__main__":
    asyncio.run(main())
