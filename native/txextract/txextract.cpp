// Native transaction signature-item extractor.
//
// The host-side producer of the verify pipeline: takes a raw serialized
// transaction region (a block's tx area or concatenated loose txs) and emits,
// per verifiable input, exactly the 32-byte big-endian buffers the rest of
// the native path consumes (secp_prepare_batch / secp_verify_batch in
// native/secp256k1/secp256k1.cpp):
//
//     z (sighash mod n) | px | py (decompressed pubkey) | r | s | present
//
// plus per-item (tx_index, input_index) and per-tx (txid, stats) metadata.
//
// Semantics are a bit-exact mirror of the Python reference path
// (tpunode/txverify.py + tpunode/sighash.py + ecdsa_cpu.decode_pubkey /
// parse_der_signature) — the parity test suite checks item-for-item
// equality on randomized workloads.  The reference node outsources all of
// this to haskoin-core/libsecp256k1 (SURVEY.md C6/C9); this is the
// TPU-framework's native equivalent of that hot path.
//
// Build: make -C native build/libtxextract.so
// Python binding: tpunode/txextract.py (ctypes).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), streaming.
// ---------------------------------------------------------------------------

struct Sha256 {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t len = 0;

  Sha256() { reset(); }

  void reset() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(h));
    len = 0;
  }

  static uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void block(const uint8_t *p) {
    static const uint32_t K[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
        0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
        0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
        0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
        0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
        0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
        0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
        0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
        0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (uint32_t(p[i * 4]) << 24) | (uint32_t(p[i * 4 + 1]) << 16) |
             (uint32_t(p[i * 4 + 2]) << 8) | p[i * 4 + 3];
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t *p, size_t n) {
    size_t fill = len % 64;
    len += n;
    if (fill) {
      size_t take = 64 - fill;
      if (take > n) take = n;
      memcpy(buf + fill, p, take);
      p += take;
      n -= take;
      if (fill + take == 64) block(buf);
      else return;
    }
    while (n >= 64) {
      block(p);
      p += 64;
      n -= 64;
    }
    if (n) memcpy(buf, p, n);
  }

  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (len % 64 != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    update(lenb, 8);
    for (int i = 0; i < 8; ++i) {
      out[i * 4] = uint8_t(h[i] >> 24);
      out[i * 4 + 1] = uint8_t(h[i] >> 16);
      out[i * 4 + 2] = uint8_t(h[i] >> 8);
      out[i * 4 + 3] = uint8_t(h[i]);
    }
  }
};

void sha256(const uint8_t *p, size_t n, uint8_t out[32]) {
  Sha256 c;
  c.update(p, n);
  c.final(out);
}

void dsha256(const uint8_t *p, size_t n, uint8_t out[32]) {
  uint8_t t[32];
  sha256(p, n, t);
  sha256(t, 32, out);
}

// ---------------------------------------------------------------------------
// RIPEMD-160 (for hash160 of the pubkey -> P2PKH script code).
// ---------------------------------------------------------------------------

struct Ripemd160 {
  static uint32_t rol(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }
  static uint32_t f(int j, uint32_t x, uint32_t y, uint32_t z) {
    if (j < 16) return x ^ y ^ z;
    if (j < 32) return (x & y) | (~x & z);
    if (j < 48) return (x | ~y) ^ z;
    if (j < 64) return (x & z) | (y & ~z);
    return x ^ (y | ~z);
  }

  static void hash(const uint8_t *msg, size_t n, uint8_t out[20]) {
    static const int r1[80] = {
        0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
        7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
        3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
        1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
        4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13};
    static const int r2[80] = {
        5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
        6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
        15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
        8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
        12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11};
    static const int s1[80] = {
        11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
        7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
        11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
        11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
        9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6};
    static const int s2[80] = {
        8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
        9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
        9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
        15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
        8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11};
    static const uint32_t K1[5] = {0, 0x5a827999, 0x6ed9eba1, 0x8f1bbcdc,
                                   0xa953fd4e};
    static const uint32_t K2[5] = {0x50a28be6, 0x5c4dd124, 0x6d703ef3,
                                   0x7a6d76e9, 0};
    uint32_t h[5] = {0x67452301, 0xefcdab89, 0x98badcfe, 0x10325476,
                     0xc3d2e1f0};
    // pad
    std::vector<uint8_t> m(msg, msg + n);
    m.push_back(0x80);
    while (m.size() % 64 != 56) m.push_back(0);
    uint64_t bits = uint64_t(n) * 8;
    for (int i = 0; i < 8; ++i) m.push_back(uint8_t(bits >> (8 * i)));
    for (size_t off = 0; off < m.size(); off += 64) {
      uint32_t x[16];
      for (int i = 0; i < 16; ++i)
        x[i] = uint32_t(m[off + i * 4]) | (uint32_t(m[off + i * 4 + 1]) << 8) |
               (uint32_t(m[off + i * 4 + 2]) << 16) |
               (uint32_t(m[off + i * 4 + 3]) << 24);
      uint32_t a1 = h[0], b1 = h[1], c1 = h[2], d1 = h[3], e1 = h[4];
      uint32_t a2 = a1, b2 = b1, c2 = c1, d2 = d1, e2 = e1;
      for (int j = 0; j < 80; ++j) {
        uint32_t t = rol(a1 + f(j, b1, c1, d1) + x[r1[j]] + K1[j / 16], s1[j]) + e1;
        a1 = e1; e1 = d1; d1 = rol(c1, 10); c1 = b1; b1 = t;
        t = rol(a2 + f(79 - j, b2, c2, d2) + x[r2[j]] + K2[j / 16], s2[j]) + e2;
        a2 = e2; e2 = d2; d2 = rol(c2, 10); c2 = b2; b2 = t;
      }
      uint32_t t = h[1] + c1 + d2;
      h[1] = h[2] + d1 + e2;
      h[2] = h[3] + e1 + a2;
      h[3] = h[4] + a1 + b2;
      h[4] = h[0] + b1 + c2;
      h[0] = t;
    }
    for (int i = 0; i < 5; ++i) {
      out[i * 4] = uint8_t(h[i]);
      out[i * 4 + 1] = uint8_t(h[i] >> 8);
      out[i * 4 + 2] = uint8_t(h[i] >> 16);
      out[i * 4 + 3] = uint8_t(h[i] >> 24);
    }
  }
};

void hash160(const uint8_t *p, size_t n, uint8_t out[20]) {
  uint8_t s[32];
  sha256(p, n, s);
  Ripemd160::hash(s, 32, out);
}

// ---------------------------------------------------------------------------
// secp256k1 base field (mod p) — only what pubkey decompression needs.
// Independent of native/secp256k1/secp256k1.cpp (that unit verifies;
// this one parses) so neither build depends on the other.
// ---------------------------------------------------------------------------

typedef unsigned __int128 u128;

struct F4 {
  uint64_t v[4];  // little-endian limbs
};

const uint64_t P_LIMBS[4] = {0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                             0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL};
const uint64_t FOLD_K = 0x1000003D1ULL;  // 2^256 mod p

bool f_ge_p(const F4 &a) {
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] > P_LIMBS[i]) return true;
    if (a.v[i] < P_LIMBS[i]) return false;
  }
  return true;  // equal
}

void f_sub_p(F4 &a) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - P_LIMBS[i] - borrow;
    a.v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
}

void f_normalize(F4 &a) {
  while (f_ge_p(a)) f_sub_p(a);
}

void f_mul(F4 &out, const F4 &a, const F4 &b) {
  uint64_t t[8] = {0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a.v[i] * b.v[j] + t[i + j] + carry;
      t[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    t[i + 4] += (uint64_t)carry;
  }
  // fold high 256 bits: r = lo + hi * FOLD_K
  uint64_t r[5] = {0};
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 cur = (u128)t[i] + (u128)t[i + 4] * FOLD_K + carry;
    r[i] = (uint64_t)cur;
    carry = cur >> 64;
  }
  r[4] = (uint64_t)carry;
  // fold the (small) carry limb once more
  u128 cur = (u128)r[0] + (u128)r[4] * FOLD_K;
  F4 res;
  res.v[0] = (uint64_t)cur;
  carry = cur >> 64;
  for (int i = 1; i < 4; ++i) {
    cur = (u128)r[i] + carry;
    res.v[i] = (uint64_t)cur;
    carry = cur >> 64;
  }
  if (carry) {  // wrapped past 2^256: add FOLD_K (== 2^256 mod p)
    cur = (u128)res.v[0] + FOLD_K;
    res.v[0] = (uint64_t)cur;
    carry = cur >> 64;
    for (int i = 1; carry && i < 4; ++i) {
      cur = (u128)res.v[i] + carry;
      res.v[i] = (uint64_t)cur;
      carry = cur >> 64;
    }
  }
  f_normalize(res);
  out = res;
}

void f_sqr(F4 &out, const F4 &a) { f_mul(out, a, a); }

void f_add(F4 &out, const F4 &a, const F4 &b) {
  u128 carry = 0;
  F4 res;
  for (int i = 0; i < 4; ++i) {
    u128 cur = (u128)a.v[i] + b.v[i] + carry;
    res.v[i] = (uint64_t)cur;
    carry = cur >> 64;
  }
  if (carry) {
    u128 cur = (u128)res.v[0] + FOLD_K;
    res.v[0] = (uint64_t)cur;
    carry = cur >> 64;
    for (int i = 1; carry && i < 4; ++i) {
      cur = (u128)res.v[i] + carry;
      res.v[i] = (uint64_t)cur;
      carry = cur >> 64;
    }
  }
  f_normalize(res);
  out = res;
}

bool f_is_eq(const F4 &a, const F4 &b) {
  return memcmp(a.v, b.v, sizeof(a.v)) == 0;
}

void f_from_be(F4 &out, const uint8_t b[32]) {
  for (int i = 0; i < 4; ++i) {
    uint64_t limb = 0;
    for (int j = 0; j < 8; ++j) limb = (limb << 8) | b[(3 - i) * 8 + j];
    out.v[i] = limb;
  }
}

void f_to_be(const F4 &a, uint8_t out[32]) {
  for (int i = 0; i < 4; ++i)
    for (int j = 0; j < 8; ++j)
      out[(3 - i) * 8 + j] = uint8_t(a.v[i] >> (56 - 8 * j));
}

// a^((p+1)/4) mod p: square root when a is a quadratic residue.
// (p+1)/4 = 2^254 - 2^30 - 244, whose bits are long runs of ones:
//   ((2^223-1) << 23 | (2^22-1)) << 6 | (2^2-1)) << 2
// so an addition chain over x^(2^k - 1) blocks costs ~253 squarings +
// 14 multiplies instead of ~500 ops for plain square-and-multiply —
// this is the hot op of pubkey decompression (one per compressed key).
void f_sqrt_candidate(F4 &out, const F4 &a) {
  F4 x2, x3, x6, x9, x11, x22, x44, x88, x176, x220, x223, t;
  auto sqn = [](F4 &r, const F4 &v, int n) {
    r = v;
    for (int i = 0; i < n; ++i) f_sqr(r, r);
  };
  f_sqr(x2, a);
  f_mul(x2, x2, a);  // x^(2^2 - 1)
  f_sqr(x3, x2);
  f_mul(x3, x3, a);  // x^(2^3 - 1)
  sqn(t, x3, 3);
  f_mul(x6, t, x3);
  sqn(t, x6, 3);
  f_mul(x9, t, x3);
  sqn(t, x9, 2);
  f_mul(x11, t, x2);
  sqn(t, x11, 11);
  f_mul(x22, t, x11);
  sqn(t, x22, 22);
  f_mul(x44, t, x22);
  sqn(t, x44, 44);
  f_mul(x88, t, x44);
  sqn(t, x88, 88);
  f_mul(x176, t, x88);
  sqn(t, x176, 44);
  f_mul(x220, t, x44);
  sqn(t, x220, 3);
  f_mul(x223, t, x3);  // x^(2^223 - 1)
  sqn(t, x223, 23);
  f_mul(t, t, x22);
  sqn(t, t, 6);
  f_mul(t, t, x2);
  sqn(t, t, 2);
  out = t;
}

// Decode a SEC1 pubkey into affine (x, y).  Mirrors ecdsa_cpu.decode_pubkey:
// returns false (present=0, auto-invalid) for malformed / off-curve keys.
bool decode_pubkey(const uint8_t *data, size_t len, uint8_t px[32],
                   uint8_t py[32]) {
  static const F4 B7 = {{7, 0, 0, 0}};
  if (len == 33 && (data[0] == 2 || data[0] == 3)) {
    F4 x;
    f_from_be(x, data + 1);
    if (f_ge_p(x)) return false;
    F4 y2, x2;
    f_sqr(x2, x);
    f_mul(y2, x2, x);
    f_add(y2, y2, B7);
    F4 y;
    f_sqrt_candidate(y, y2);
    F4 check;
    f_sqr(check, y);
    if (!f_is_eq(check, y2)) return false;  // non-residue: not on curve
    if ((y.v[0] & 1) != (data[0] & 1)) {
      // y = p - y
      F4 neg = {{P_LIMBS[0], P_LIMBS[1], P_LIMBS[2], P_LIMBS[3]}};
      u128 borrow = 0;
      for (int i = 0; i < 4; ++i) {
        u128 d = (u128)neg.v[i] - y.v[i] - borrow;
        neg.v[i] = (uint64_t)d;
        borrow = (d >> 64) & 1;
      }
      y = neg;
    }
    f_to_be(x, px);
    f_to_be(y, py);
    return true;
  }
  if (len == 65 && data[0] == 4) {
    F4 x, y;
    f_from_be(x, data + 1);
    f_from_be(y, data + 33);
    if (f_ge_p(x) || f_ge_p(y)) return false;
    // on-curve check: y^2 == x^3 + 7.  (0,0) fails: 0 != 7 — matching the
    // oracle, which treats the infinity encoding as not-on-curve.
    F4 lhs, x2, rhs;
    f_sqr(lhs, y);
    f_sqr(x2, x);
    f_mul(rhs, x2, x);
    f_add(rhs, rhs, B7);
    if (!f_is_eq(lhs, rhs)) return false;
    memcpy(px, data + 1, 32);
    memcpy(py, data + 33, 32);
    return true;
  }
  return false;
}

// BIP340 lift_x: the EVEN-y point with x-coordinate `x32` (big-endian).
// Mirrors ecdsa_cpu.lift_x — taproot output keys are x-only; an off-curve
// x makes the spend consensus-invalid.
bool lift_x(const uint8_t x32[32], uint8_t px[32], uint8_t py[32]) {
  static const F4 B7 = {{7, 0, 0, 0}};
  F4 x;
  f_from_be(x, x32);
  if (f_ge_p(x)) return false;
  F4 y2, x2;
  f_sqr(x2, x);
  f_mul(y2, x2, x);
  f_add(y2, y2, B7);
  F4 y;
  f_sqrt_candidate(y, y2);
  F4 check;
  f_sqr(check, y);
  if (!f_is_eq(check, y2)) return false;  // non-residue: not on curve
  if (y.v[0] & 1) {
    // y = p - y (pick the even root)
    F4 neg = {{P_LIMBS[0], P_LIMBS[1], P_LIMBS[2], P_LIMBS[3]}};
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
      u128 d = (u128)neg.v[i] - y.v[i] - borrow;
      neg.v[i] = (uint64_t)d;
      borrow = (d >> 64) & 1;
    }
    y = neg;
  }
  memcpy(px, x32, 32);
  f_to_be(y, py);
  return true;
}

// BIP340-style tagged hash: SHA256(SHA256(tag) || SHA256(tag) || data).
// The two tag digests taproot needs are computed once per process.
struct TagMidstate {
  uint8_t th[32];
  explicit TagMidstate(const char *tag) {
    sha256(reinterpret_cast<const uint8_t *>(tag), strlen(tag), th);
  }
};

void tagged_hash_init(Sha256 &h, const TagMidstate &tag) {
  h.update(tag.th, 32);
  h.update(tag.th, 32);
}

const TagMidstate &tap_sighash_tag() {
  static const TagMidstate t("TapSighash");
  return t;
}

const TagMidstate &bip340_challenge_tag() {
  static const TagMidstate t("BIP0340/challenge");
  return t;
}

const TagMidstate &tap_leaf_tag() {
  static const TagMidstate t("TapLeaf");
  return t;
}

// Curve order n, big-endian — sighash digests are reduced mod n before
// packing (parity with NativeVerifier.verify_batch's `z % CURVE_N`).
const uint8_t N_BE[32] = {0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF,
                          0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFE,
                          0xBA, 0xAE, 0xDC, 0xE6, 0xAF, 0x48, 0xA0, 0x3B,
                          0xBF, 0xD2, 0x5E, 0x8C, 0xD0, 0x36, 0x41, 0x41};

void reduce_mod_n(uint8_t z[32]) {
  if (memcmp(z, N_BE, 32) < 0) return;  // z < n (z < 2^256 < 2n: one sub)
  int borrow = 0;
  for (int i = 31; i >= 0; --i) {
    int d = int(z[i]) - int(N_BE[i]) - borrow;
    borrow = d < 0;
    z[i] = uint8_t(d & 0xFF);
  }
}

// ---------------------------------------------------------------------------
// Wire parsing (mirrors tpunode/wire.py Reader/Tx.deserialize).
// ---------------------------------------------------------------------------

struct Cursor {
  const uint8_t *p;
  const uint8_t *end;
  bool ok = true;

  size_t remaining() const { return size_t(end - p); }

  bool need(size_t n) {
    if (!ok || remaining() < n) {
      ok = false;
      return false;
    }
    return true;
  }

  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v = uint32_t(p[0]) | (uint32_t(p[1]) << 8) |
                 (uint32_t(p[2]) << 16) | (uint32_t(p[3]) << 24);
    p += 4;
    return v;
  }

  uint64_t u64() {
    if (!need(8)) return 0;
    uint64_t v = 0;
    for (int i = 7; i >= 0; --i) v = (v << 8) | p[i];
    p += 8;
    return v;
  }

  uint64_t varint() {
    // Rejects non-minimal encodings (Bitcoin Core ReadCompactSize): txid
    // and sighash here are dsha256 over RAW spans, so accepting e.g. an
    // input count of "fd 01 00" would hash different bytes than the
    // canonically re-serializing Python reference path.
    if (!need(1)) return 0;
    uint8_t first = *p++;
    if (first < 0xFD) return first;
    uint64_t v, lo;
    if (first == 0xFD) {
      if (!need(2)) return 0;
      v = uint64_t(p[0]) | (uint64_t(p[1]) << 8);
      p += 2;
      lo = 0xFD;
    } else if (first == 0xFE) {
      v = u32();
      lo = 0x10000;
    } else {
      v = u64();
      lo = 0x100000000ULL;
    }
    if (ok && v < lo) ok = false;
    return ok ? v : 0;
  }

  const uint8_t *bytes(size_t n) {
    if (!need(n)) return nullptr;
    const uint8_t *r = p;
    p += n;
    return r;
  }
};

// Witness spans kept per input: enough for every template we extract
// (multisig needs dummy + 16 sigs + script = 18); larger witnesses keep
// their true count but only the first spans, and no template matches them.
const int MAX_WIT_SPANS = 19;

struct InSpan {
  const uint8_t *prevout;  // 36 bytes (txid + index)
  const uint8_t *script;
  uint32_t script_len;
  uint32_t sequence;
  uint32_t wit_count = 0;
  const uint8_t *wit[MAX_WIT_SPANS];
  uint32_t wit_len[MAX_WIT_SPANS];
};

struct OutSpan {
  const uint8_t *start;  // value(8) + varstr(script): contiguous raw span
  uint32_t len;
  int64_t value;
};

struct TxSpan {
  const uint8_t *version;        // 4 bytes
  const uint8_t *inout_start;    // varint(n_in) .. outputs end (witness-free)
  uint32_t inout_len;
  const uint8_t *locktime;       // 4 bytes
  const uint8_t *outputs_start;  // contiguous serialized outputs region
  uint32_t outputs_len;
  std::vector<InSpan> ins;
  std::vector<OutSpan> outs;
  uint8_t txid[32];
  // lazy BIP143 per-tx caches (flag 1 = computed)
  uint8_t hash_prevouts[32], hash_sequence[32], hash_outputs[32];
  bool hp = false, hs = false, ho = false;
};

// Parse one tx at the cursor.  Returns false on malformed data.
bool parse_tx(Cursor &c, TxSpan &tx, bool compute_txid) {
  tx.version = c.bytes(4);
  if (!c.ok) return false;
  bool segwit = c.remaining() >= 2 && c.p[0] == 0x00 && c.p[1] == 0x01;
  if (segwit) c.p += 2;
  tx.inout_start = c.p;
  uint64_t n_in = c.varint();
  // Clamp by the minimum encoded size (36B prevout + 1B script len + 4B
  // sequence) BEFORE allocating: a tiny malformed buffer claiming 2^24
  // inputs must fail here, not after a GB-scale transient resize.
  if (!c.ok || n_in > c.remaining() / 41) return false;
  tx.ins.resize(n_in);
  for (uint64_t i = 0; i < n_in; ++i) {
    InSpan &in = tx.ins[i];
    in.prevout = c.bytes(36);
    uint64_t slen = c.varint();
    if (!c.ok || slen > c.remaining()) return false;
    in.script = c.bytes(slen);
    in.script_len = uint32_t(slen);
    in.sequence = c.u32();
    if (!c.ok) return false;
  }
  uint64_t n_out = c.varint();
  // Same pre-allocation clamp: an output is at least value(8) + varstr(1).
  if (!c.ok || n_out > c.remaining() / 9) return false;
  tx.outs.resize(n_out);
  tx.outputs_start = c.p;
  for (uint64_t i = 0; i < n_out; ++i) {
    OutSpan &out = tx.outs[i];
    out.start = c.p;
    out.value = int64_t(c.u64());
    uint64_t slen = c.varint();
    if (!c.ok || slen > c.remaining()) return false;
    c.bytes(slen);
    out.len = uint32_t(c.p - out.start);
    if (!c.ok) return false;
  }
  tx.outputs_len = uint32_t(c.p - tx.outputs_start);
  tx.inout_len = uint32_t(c.p - tx.inout_start);
  if (segwit) {
    for (uint64_t i = 0; i < n_in; ++i) {
      uint64_t cnt = c.varint();
      if (!c.ok || cnt > (1u << 20)) return false;
      InSpan &in = tx.ins[i];
      in.wit_count = uint32_t(cnt);
      for (uint64_t w = 0; w < cnt; ++w) {
        uint64_t wlen = c.varint();
        if (!c.ok || wlen > c.remaining()) return false;
        const uint8_t *wp = c.bytes(wlen);
        if (w < MAX_WIT_SPANS) {
          in.wit[w] = wp;
          in.wit_len[w] = uint32_t(wlen);
        }
      }
    }
  }
  tx.locktime = c.bytes(4);
  if (!c.ok) return false;
  if (compute_txid) {
    // txid = dsha256 of the witness-stripped serialization
    Sha256 h1;
    h1.update(tx.version, 4);
    h1.update(tx.inout_start, tx.inout_len);
    h1.update(tx.locktime, 4);
    uint8_t t[32];
    h1.final(t);
    sha256(t, 32, tx.txid);
  }
  return true;
}

// ---------------------------------------------------------------------------
// DER signature parsing (mirrors ecdsa_cpu.parse_der_signature's lax rules).
// r/s land right-aligned in 32-byte big-endian buffers; values with more
// than 32 significant bytes (> 2^256, possible under lax DER) come out as
// zero — zero fails the 0 < r,s < n range check downstream exactly like the
// oversized original would, with no aliasing.
// ---------------------------------------------------------------------------

bool parse_der(const uint8_t *sig, size_t len, uint8_t r[32], uint8_t s[32]) {
  if (len < 8 || sig[0] != 0x30) return false;
  if (sig[1] != len - 2) return false;
  if (sig[2] != 0x02) return false;
  size_t rlen = sig[3];
  size_t pos = 4 + rlen;
  if (pos + 1 >= len) return false;  // need the 0x02 and slen bytes
  if (sig[pos] != 0x02) return false;
  size_t slen = sig[pos + 1];
  if (pos + 2 + slen != len) return false;
  const uint8_t *rp = sig + 4;
  const uint8_t *sp = sig + pos + 2;
  // strip leading zeros; reject (as out-of-range zero) if > 32 bytes remain
  while (rlen > 0 && *rp == 0) { ++rp; --rlen; }
  while (slen > 0 && *sp == 0) { ++sp; --slen; }
  memset(r, 0, 32);
  memset(s, 0, 32);
  if (rlen <= 32) memcpy(r + 32 - rlen, rp, rlen);
  if (slen <= 32) memcpy(s + 32 - slen, sp, slen);
  return true;
}

// Parse a pushes-only script (OP_0, opcodes 1-75, PUSHDATA1/2) — mirror of
// txverify._parse_pushes.  OP_0 parses as an empty push (the CHECKMULTISIG
// dummy).  Fills at most `max_out` spans; returns the push count or -1 if
// any non-push opcode appears.
int parse_pushes(const uint8_t *script, size_t n,
                 const uint8_t **out, size_t *out_len, int max_out) {
  int count = 0;
  size_t i = 0;
  while (i < n) {
    uint8_t op = script[i++];
    size_t ln;
    if (op == 0) {
      ln = 0;
    } else if (op >= 1 && op <= 75) {
      ln = op;
    } else if (op == 76 && i < n) {
      ln = script[i++];
    } else if (op == 77 && i + 1 < n) {
      ln = size_t(script[i]) | (size_t(script[i + 1]) << 8);
      i += 2;
    } else {
      return -1;
    }
    if (i + ln > n) return -1;
    if (count < max_out) {
      out[count] = script + i;
      out_len[count] = ln;
    }
    ++count;
    i += ln;
  }
  return count;
}

// Bare multisig template: OP_m <key>*n OP_n OP_CHECKMULTISIG, keys 33/65
// bytes — mirror of txverify._parse_multisig.
struct MsigTemplate {
  int m = 0, n = 0;
  const uint8_t *keys[16];
  size_t key_len[16];
};

bool parse_multisig(const uint8_t *s, size_t len, MsigTemplate &out) {
  if (len < 3 || s[len - 1] != 0xAE) return false;
  int n_op = s[len - 2], m_op = s[0];
  if (n_op < 0x51 || n_op > 0x60 || m_op < 0x51 || m_op > 0x60) return false;
  out.n = n_op - 0x50;
  out.m = m_op - 0x50;
  if (out.m > out.n) return false;
  size_t i = 1, end = len - 2;
  int k = 0;
  while (i < end) {
    size_t ln = s[i++];
    if ((ln != 33 && ln != 65) || i + ln > end || k >= 16) return false;
    out.keys[k] = s + i;
    out.key_len[k] = ln;
    ++k;
    i += ln;
  }
  return k == out.n;
}

// Bare P2PK template <33/65-byte pubkey> OP_CHECKSIG (also the P2WSH
// single-key witness-script shape); returns the key span or nullptr.
const uint8_t *is_p2pk_script(const uint8_t *s, uint32_t len,
                              size_t *key_len) {
  if (len == 35 && s[0] == 33 && s[34] == 0xAC) {
    *key_len = 33;
    return s + 1;
  }
  if (len == 67 && s[0] == 65 && s[66] == 0xAC) {
    *key_len = 65;
    return s + 1;
  }
  return nullptr;
}

// Single-push scriptSig (the bare-P2PK spend shape) — mirror of the
// wants_amount shape check.
bool single_push_script_sig(const InSpan &in) {
  return in.script_len >= 10 && in.script_len == uint32_t(in.script[0]) + 1;
}

// The spend-template classifier shared by txx_scan (capacity) and
// txx_extract (emission) — mirror of the template dispatch in
// txverify.extract_sig_items.
struct InTemplate {
  enum Kind { UNSUPPORTED, SINGLE, MULTISIG } kind = UNSUPPORTED;
  bool segwit = false;  // BIP143 digest (amount required)
  const uint8_t *sig = nullptr;  // SINGLE
  size_t sig_len = 0;
  const uint8_t *pub = nullptr;
  size_t pub_len = 0;
  MsigTemplate ms;  // MULTISIG
  const uint8_t *sigs[16];
  size_t sig_lens[16];
  // script_code: redeem/witness script for MULTISIG; for SINGLE, set
  // only when it is NOT the derived P2PKH template (P2WSH single-key's
  // witness script, bare P2PK's prevout script)
  const uint8_t *sc = nullptr;
  size_t sc_len = 0;
};

// P2WSH multisig witness shape: [<empty dummy>, <sig>*m, script].
bool is_msig_witness(const InSpan &in, InTemplate &t) {
  if (in.wit_count < 3 || in.wit_count > 18) return false;
  if (in.wit_len[0] != 0) return false;
  uint32_t last = in.wit_count - 1;
  if (!parse_multisig(in.wit[last], in.wit_len[last], t.ms)) return false;
  if (int(in.wit_count) - 2 != t.ms.m) return false;
  for (int i = 0; i < t.ms.m; ++i) {
    t.sigs[i] = in.wit[1 + i];
    t.sig_lens[i] = in.wit_len[1 + i];
  }
  t.sc = in.wit[last];
  t.sc_len = in.wit_len[last];
  return true;
}

void classify_input(const InSpan &in, InTemplate &t) {
  if (in.script_len == 0 && in.wit_count == 2) {
    if (in.wit_len[1] == 33 || in.wit_len[1] == 65) {
      // P2WPKH: [sig, pubkey]
      t.kind = InTemplate::SINGLE;
      t.segwit = true;
      t.sig = in.wit[0]; t.sig_len = in.wit_len[0];
      t.pub = in.wit[1]; t.pub_len = in.wit_len[1];
      return;
    }
    size_t klen;
    const uint8_t *key = is_p2pk_script(in.wit[1], in.wit_len[1], &klen);
    if (key != nullptr) {
      // P2WSH single-key: [sig, <key> OP_CHECKSIG]; the witness script
      // is the BIP143 script_code (a non-matching 2-element witness is
      // UNSUPPORTED, not auto-invalid — mirror of txverify)
      t.kind = InTemplate::SINGLE;
      t.segwit = true;
      t.sig = in.wit[0]; t.sig_len = in.wit_len[0];
      t.pub = key; t.pub_len = klen;
      t.sc = in.wit[1]; t.sc_len = in.wit_len[1];
    }
    return;
  }
  if (in.script_len == 0 && is_msig_witness(in, t)) {
    t.kind = InTemplate::MULTISIG;
    t.segwit = true;
    return;
  }
  const uint8_t *pushes[MAX_WIT_SPANS];
  size_t plen[MAX_WIT_SPANS];
  int np = parse_pushes(in.script, in.script_len, pushes, plen, MAX_WIT_SPANS);
  if (np == 2 && (plen[1] == 33 || plen[1] == 65)) {
    // P2PKH
    t.kind = InTemplate::SINGLE;
    t.sig = pushes[0]; t.sig_len = plen[0];
    t.pub = pushes[1]; t.pub_len = plen[1];
    return;
  }
  if (np == 1 && plen[0] == 22 && pushes[0][0] == 0x00 &&
      pushes[0][1] == 0x14 && in.wit_count == 2) {
    // P2SH-P2WPKH
    t.kind = InTemplate::SINGLE;
    t.segwit = true;
    t.sig = in.wit[0]; t.sig_len = in.wit_len[0];
    t.pub = in.wit[1]; t.pub_len = in.wit_len[1];
    return;
  }
  if (np == 1 && plen[0] == 34 && pushes[0][0] == 0x00 &&
      pushes[0][1] == 0x20 && is_msig_witness(in, t)) {
    // P2SH-P2WSH multisig
    t.kind = InTemplate::MULTISIG;
    t.segwit = true;
    return;
  }
  if (np == 1 && plen[0] == 34 && pushes[0][0] == 0x00 &&
      pushes[0][1] == 0x20 && in.wit_count == 2) {
    size_t klen;
    const uint8_t *key = is_p2pk_script(in.wit[1], in.wit_len[1], &klen);
    if (key != nullptr) {
      // P2SH-P2WSH single-key
      t.kind = InTemplate::SINGLE;
      t.segwit = true;
      t.sig = in.wit[0]; t.sig_len = in.wit_len[0];
      t.pub = key; t.pub_len = klen;
      t.sc = in.wit[1]; t.sc_len = in.wit_len[1];
      return;
    }
  }
  if (np >= 2 && np <= 18 && plen[0] == 0 &&
      parse_multisig(pushes[np - 1], plen[np - 1], t.ms) &&
      np - 2 == t.ms.m) {
    // legacy P2SH multisig: OP_0 <sig>*m <redeemScript>
    t.kind = InTemplate::MULTISIG;
    for (int i = 0; i < t.ms.m; ++i) {
      t.sigs[i] = pushes[1 + i];
      t.sig_lens[i] = plen[1 + i];
    }
    t.sc = pushes[np - 1];
    t.sc_len = plen[np - 1];
    return;
  }
}

// ---------------------------------------------------------------------------
// Sighash computation (mirrors tpunode/sighash.py byte for byte).
// ---------------------------------------------------------------------------

const int SIGHASH_NONE = 2, SIGHASH_SINGLE = 3;
const int SIGHASH_ANYONECANPAY = 0x80, SIGHASH_FORKID = 0x40;

void put_varint(std::vector<uint8_t> &buf, uint64_t n) {
  if (n < 0xFD) {
    buf.push_back(uint8_t(n));
  } else if (n <= 0xFFFF) {
    buf.push_back(0xFD);
    buf.push_back(uint8_t(n));
    buf.push_back(uint8_t(n >> 8));
  } else if (n <= 0xFFFFFFFFULL) {
    buf.push_back(0xFE);
    for (int i = 0; i < 4; ++i) buf.push_back(uint8_t(n >> (8 * i)));
  } else {
    buf.push_back(0xFF);
    for (int i = 0; i < 8; ++i) buf.push_back(uint8_t(n >> (8 * i)));
  }
}

void put_u32(std::vector<uint8_t> &buf, uint32_t v) {
  for (int i = 0; i < 4; ++i) buf.push_back(uint8_t(v >> (8 * i)));
}

// Legacy (pre-segwit) digest -> out[32] big-endian (already the z bytes).
void legacy_sighash(const TxSpan &tx, size_t index, const uint8_t *script_code,
                    size_t sc_len, int hashtype, std::vector<uint8_t> &scratch,
                    uint8_t out[32]) {
  int base = hashtype & 0x1F;
  if (base == SIGHASH_SINGLE && index >= tx.outs.size()) {
    memset(out, 0, 32);
    out[31] = 1;  // the historical "hash = 1" quirk
    return;
  }
  scratch.clear();
  std::vector<uint8_t> &buf = scratch;
  buf.insert(buf.end(), tx.version, tx.version + 4);
  if (hashtype & SIGHASH_ANYONECANPAY) {
    put_varint(buf, 1);
    const InSpan &in = tx.ins[index];
    buf.insert(buf.end(), in.prevout, in.prevout + 36);
    put_varint(buf, sc_len);
    buf.insert(buf.end(), script_code, script_code + sc_len);
    put_u32(buf, in.sequence);
  } else {
    put_varint(buf, tx.ins.size());
    for (size_t i = 0; i < tx.ins.size(); ++i) {
      const InSpan &in = tx.ins[i];
      buf.insert(buf.end(), in.prevout, in.prevout + 36);
      if (i == index) {
        put_varint(buf, sc_len);
        buf.insert(buf.end(), script_code, script_code + sc_len);
      } else {
        buf.push_back(0);
      }
      uint32_t seq = in.sequence;
      if (i != index && (base == SIGHASH_NONE || base == SIGHASH_SINGLE))
        seq = 0;
      put_u32(buf, seq);
    }
  }
  if (base == SIGHASH_NONE) {
    put_varint(buf, 0);
  } else if (base == SIGHASH_SINGLE) {
    put_varint(buf, index + 1);
    for (size_t i = 0; i < index; ++i) {
      for (int k = 0; k < 8; ++k) buf.push_back(0xFF);  // value = -1
      buf.push_back(0);                                 // empty script
    }
    const OutSpan &o = tx.outs[index];
    buf.insert(buf.end(), o.start, o.start + o.len);
  } else {
    put_varint(buf, tx.outs.size());
    buf.insert(buf.end(), tx.outputs_start, tx.outputs_start + tx.outputs_len);
  }
  buf.insert(buf.end(), tx.locktime, tx.locktime + 4);
  put_u32(buf, uint32_t(hashtype));
  dsha256(buf.data(), buf.size(), out);
}

// BIP143 (segwit v0 / BCH FORKID) digest -> out[32].
void bip143_sighash(TxSpan &tx, size_t index, const uint8_t *script_code,
                    size_t sc_len, int64_t amount, int hashtype,
                    std::vector<uint8_t> &scratch, uint8_t out[32]) {
  int base = hashtype & 0x1F;
  bool acp = (hashtype & SIGHASH_ANYONECANPAY) != 0;
  uint8_t zero32[32] = {0};
  const uint8_t *hash_prevouts = zero32, *hash_sequence = zero32,
                *hash_outputs = zero32;
  uint8_t single_out[32];
  if (!acp) {
    if (!tx.hp) {
      Sha256 h;
      for (const InSpan &in : tx.ins) h.update(in.prevout, 36);
      uint8_t t[32];
      h.final(t);
      sha256(t, 32, tx.hash_prevouts);
      tx.hp = true;
    }
    hash_prevouts = tx.hash_prevouts;
  }
  if (!acp && base != SIGHASH_NONE && base != SIGHASH_SINGLE) {
    if (!tx.hs) {
      Sha256 h;
      for (const InSpan &in : tx.ins) {
        uint8_t seq[4] = {uint8_t(in.sequence), uint8_t(in.sequence >> 8),
                          uint8_t(in.sequence >> 16),
                          uint8_t(in.sequence >> 24)};
        h.update(seq, 4);
      }
      uint8_t t[32];
      h.final(t);
      sha256(t, 32, tx.hash_sequence);
      tx.hs = true;
    }
    hash_sequence = tx.hash_sequence;
  }
  if (base != SIGHASH_NONE && base != SIGHASH_SINGLE) {
    if (!tx.ho) {
      dsha256(tx.outputs_start, tx.outputs_len, tx.hash_outputs);
      tx.ho = true;
    }
    hash_outputs = tx.hash_outputs;
  } else if (base == SIGHASH_SINGLE && index < tx.outs.size()) {
    dsha256(tx.outs[index].start, tx.outs[index].len, single_out);
    hash_outputs = single_out;
  }
  scratch.clear();
  std::vector<uint8_t> &buf = scratch;
  const InSpan &in = tx.ins[index];
  buf.insert(buf.end(), tx.version, tx.version + 4);
  buf.insert(buf.end(), hash_prevouts, hash_prevouts + 32);
  buf.insert(buf.end(), hash_sequence, hash_sequence + 32);
  buf.insert(buf.end(), in.prevout, in.prevout + 36);
  put_varint(buf, sc_len);
  buf.insert(buf.end(), script_code, script_code + sc_len);
  for (int i = 0; i < 8; ++i) buf.push_back(uint8_t(uint64_t(amount) >> (8 * i)));
  put_u32(buf, in.sequence);
  buf.insert(buf.end(), hash_outputs, hash_outputs + 32);
  buf.insert(buf.end(), tx.locktime, tx.locktime + 4);
  put_u32(buf, uint32_t(hashtype));
  dsha256(buf.data(), buf.size(), out);
}

// ---------------------------------------------------------------------------
// BIP341 (taproot) sighash — mirrors tpunode/sighash.py bip341_sighash.
// All hashes are SINGLE SHA-256 (unlike legacy/BIP143's double).
// ---------------------------------------------------------------------------

bool valid_taproot_hashtype(int ht) {
  return ht == 0x00 || ht == 0x01 || ht == 0x02 || ht == 0x03 ||
         ht == 0x81 || ht == 0x82 || ht == 0x83;
}

// Resolved prevout (amount, scriptPubKey) rows for one tx's inputs —
// BIP341 signs over the whole spent-output set.
struct TapPrevouts {
  std::vector<int64_t> amounts;
  std::vector<const uint8_t *> scripts;
  std::vector<uint32_t> script_lens;
  std::vector<bool> have;  // per input: both amount and script resolved
  bool built = false;
};

// Per-tx cache of the five whole-tx hashes (valid for one extract call:
// amounts/scripts depend on the call's ext_* resolution).
struct TapTxHashes {
  uint8_t prevouts[32], amounts[32], scriptpubkeys[32], sequences[32],
      outputs[32];
  bool pv = false, am = false, sp = false, sq = false, out = false;
};

// Signature message -> out[32]: keypath (ext_flag = 0) when `leaf_hash`
// is nullptr; script path (ext_flag = 1, BIP342 extension: tapleaf hash
// ∥ key_version 0 ∥ codesep 0xFFFFFFFF) otherwise.  `annex` is the full
// witness element (0x50-prefixed) or nullptr.  Requires tp.have[...]
// resolution per the hash_type (caller checks); returns false when the
// spend is structurally INVALID under BIP341 (bad hash_type,
// SIGHASH_SINGLE with no matching output) — the caller emits an
// auto-invalid item, not unsupported.
bool bip341_sighash(TxSpan &tx, size_t index, int hashtype,
                    const uint8_t *annex, size_t annex_len,
                    const TapPrevouts &tp, TapTxHashes &th,
                    std::vector<uint8_t> &scratch, uint8_t out[32],
                    const uint8_t *leaf_hash = nullptr) {
  if (!valid_taproot_hashtype(hashtype)) return false;
  int base = hashtype & 3;
  bool acp = (hashtype & SIGHASH_ANYONECANPAY) != 0;
  if (base == SIGHASH_SINGLE && index >= tx.outs.size()) return false;

  scratch.clear();
  std::vector<uint8_t> &buf = scratch;
  buf.push_back(uint8_t(hashtype));
  buf.insert(buf.end(), tx.version, tx.version + 4);
  buf.insert(buf.end(), tx.locktime, tx.locktime + 4);
  if (!acp) {
    if (!th.pv) {
      Sha256 h;
      for (const InSpan &in : tx.ins) h.update(in.prevout, 36);
      h.final(th.prevouts);
      th.pv = true;
    }
    if (!th.am) {
      Sha256 h;
      for (size_t i = 0; i < tx.ins.size(); ++i) {
        uint64_t a = uint64_t(tp.amounts[i]);
        uint8_t le[8];
        for (int k = 0; k < 8; ++k) le[k] = uint8_t(a >> (8 * k));
        h.update(le, 8);
      }
      h.final(th.amounts);
      th.am = true;
    }
    if (!th.sp) {
      Sha256 h;
      std::vector<uint8_t> vs;
      for (size_t i = 0; i < tx.ins.size(); ++i) {
        vs.clear();
        put_varint(vs, tp.script_lens[i]);
        h.update(vs.data(), vs.size());
        h.update(tp.scripts[i], tp.script_lens[i]);
      }
      h.final(th.scriptpubkeys);
      th.sp = true;
    }
    if (!th.sq) {
      Sha256 h;
      for (const InSpan &in : tx.ins) {
        uint8_t seq[4] = {uint8_t(in.sequence), uint8_t(in.sequence >> 8),
                          uint8_t(in.sequence >> 16),
                          uint8_t(in.sequence >> 24)};
        h.update(seq, 4);
      }
      h.final(th.sequences);
      th.sq = true;
    }
    buf.insert(buf.end(), th.prevouts, th.prevouts + 32);
    buf.insert(buf.end(), th.amounts, th.amounts + 32);
    buf.insert(buf.end(), th.scriptpubkeys, th.scriptpubkeys + 32);
    buf.insert(buf.end(), th.sequences, th.sequences + 32);
  }
  if (base != SIGHASH_NONE && base != SIGHASH_SINGLE) {
    if (!th.out) {
      sha256(tx.outputs_start, tx.outputs_len, th.outputs);
      th.out = true;
    }
    buf.insert(buf.end(), th.outputs, th.outputs + 32);
  }
  int ext_flag = leaf_hash != nullptr ? 1 : 0;
  buf.push_back(uint8_t(ext_flag * 2 + (annex != nullptr ? 1 : 0)));
  const InSpan &in = tx.ins[index];
  if (acp) {
    buf.insert(buf.end(), in.prevout, in.prevout + 36);
    uint64_t a = uint64_t(tp.amounts[index]);
    for (int k = 0; k < 8; ++k) buf.push_back(uint8_t(a >> (8 * k)));
    put_varint(buf, tp.script_lens[index]);
    buf.insert(buf.end(), tp.scripts[index],
               tp.scripts[index] + tp.script_lens[index]);
    put_u32(buf, in.sequence);
  } else {
    put_u32(buf, uint32_t(index));
  }
  if (annex != nullptr) {
    std::vector<uint8_t> va;
    put_varint(va, annex_len);
    va.insert(va.end(), annex, annex + annex_len);
    uint8_t ah[32];
    sha256(va.data(), va.size(), ah);
    buf.insert(buf.end(), ah, ah + 32);
  }
  if (base == SIGHASH_SINGLE) {
    uint8_t oh[32];
    sha256(tx.outs[index].start, tx.outs[index].len, oh);
    buf.insert(buf.end(), oh, oh + 32);
  }
  if (leaf_hash != nullptr) {
    // BIP342 extension: tapleaf ∥ key_version 0 ∥ codesep "none" sentinel
    buf.insert(buf.end(), leaf_hash, leaf_hash + 32);
    buf.push_back(0x00);
    for (int k = 0; k < 4; ++k) buf.push_back(0xFF);
  }
  Sha256 h;
  tagged_hash_init(h, tap_sighash_tag());
  uint8_t epoch = 0x00;
  h.update(&epoch, 1);
  h.update(buf.data(), buf.size());
  h.final(out);
  return true;
}

// The canonical single-key tapscript: <32-byte x-only key> OP_CHECKSIG.
bool is_single_key_tapscript(const uint8_t *s, uint32_t len) {
  return len == 34 && s[0] == 0x20 && s[33] == 0xAC;
}

// BIP341 control block: leaf version 0xC0, internal key, 0-128 path nodes.
bool valid_control_block(const uint8_t *cb, uint32_t len) {
  return len >= 33 && len <= 33 + 128 * 32 && (len - 33) % 32 == 0 &&
         (cb[0] & 0xFE) == 0xC0;
}

// Locate an output's scriptPubKey inside its raw span (value(8) +
// varstr(script)).
bool out_script(const OutSpan &o, const uint8_t **script, uint32_t *len) {
  Cursor c{o.start + 8, o.start + o.len};
  uint64_t slen = c.varint();
  if (!c.ok || slen > c.remaining()) return false;
  *script = c.p;
  *len = uint32_t(slen);
  return true;
}

bool is_p2tr_script(const uint8_t *s, uint32_t len) {
  return len == 34 && s[0] == 0x51 && s[1] == 0x20;
}

// Per-extract-call decoded-pubkey cache: decompression costs a field sqrt
// (~a modexp), and real workloads reuse keys heavily (one wallet key funds
// many inputs; multisig windows retry the same keys).  Bounded so a block
// full of distinct garbage keys cannot balloon memory.
struct PubkeyEntry {
  uint8_t px[32], py[32];
  bool ok;
};
using PubkeyCache = std::unordered_map<std::string, PubkeyEntry>;
const size_t PUBKEY_CACHE_MAX = 1 << 17;

bool decode_pubkey_cached(PubkeyCache &cache, const uint8_t *data, size_t len,
                          uint8_t px[32], uint8_t py[32]) {
  if (cache.size() >= PUBKEY_CACHE_MAX)
    return decode_pubkey(data, len, px, py);
  std::string key(reinterpret_cast<const char *>(data), len);
  auto it = cache.find(key);
  if (it == cache.end()) {
    PubkeyEntry e;
    e.ok = decode_pubkey(data, len, e.px, e.py);
    if (!e.ok) {
      memset(e.px, 0, 32);
      memset(e.py, 0, 32);
    }
    it = cache.emplace(std::move(key), e).first;
  }
  if (!it->second.ok) return false;
  memcpy(px, it->second.px, 32);
  memcpy(py, it->second.py, 32);
  return true;
}

// lift_x through a bounded cache of ITS OWN (a field sqrt per call; real
// taproot workloads reuse output/leaf keys through address reuse).  The
// cache object must be separate from the SEC1 decode cache: any in-band
// namespace tag can be forged by an attacker-controlled scriptSig pubkey
// blob of the right shape, poisoning one lane's entries with the other's
// verdicts (review r5 finding, confirmed by repro).
bool lift_x_cached(PubkeyCache &cache, const uint8_t x32[32], uint8_t px[32],
                   uint8_t py[32]) {
  if (cache.size() >= PUBKEY_CACHE_MAX) return lift_x(x32, px, py);
  std::string key(reinterpret_cast<const char *>(x32), 32);
  auto it = cache.find(key);
  if (it == cache.end()) {
    PubkeyEntry e;
    e.ok = lift_x(x32, e.px, e.py);
    if (!e.ok) {
      memset(e.px, 0, 32);
      memset(e.py, 0, 32);
    }
    it = cache.emplace(std::move(key), e).first;
  }
  if (!it->second.ok) return false;
  memcpy(px, it->second.px, 32);
  memcpy(py, it->second.py, 32);
  return true;
}

// ---------------------------------------------------------------------------
// Intra-block prevout amount map: (txid, vout) -> satoshis.
// ---------------------------------------------------------------------------

struct OutpointKey {
  uint8_t b[36];
  bool operator==(const OutpointKey &o) const {
    return memcmp(b, o.b, 36) == 0;
  }
};

struct OutpointHash {
  size_t operator()(const OutpointKey &k) const {
    uint64_t h;  // txids are uniform: first 8 bytes are a fine hash, mix vout
    memcpy(&h, k.b, 8);
    uint32_t vout;
    memcpy(&vout, k.b + 32, 4);
    return size_t(h ^ (uint64_t(vout) * 0x9E3779B97F4A7C15ULL));
  }
};

// Intra-block prevout (amount, scriptPubKey) value; the map lives on the
// parse handle so tx-range shard extractions share ONE build (read-only
// after txx_build_intra_h) instead of each rebuilding it per range.
struct PrevoutInfo {
  int64_t value;
  const uint8_t *script;
  uint32_t script_len;
};
using PrevoutMap = std::unordered_map<OutpointKey, PrevoutInfo, OutpointHash>;

void build_prevout_map(const std::vector<TxSpan> &txs, PrevoutMap &map) {
  size_t total_outs = 0;
  for (const TxSpan &tx : txs) total_outs += tx.outs.size();
  map.reserve(total_outs * 2);
  for (const TxSpan &tx : txs) {
    for (size_t vout = 0; vout < tx.outs.size(); ++vout) {
      OutpointKey key;
      memcpy(key.b, tx.txid, 32);
      uint32_t v32 = uint32_t(vout);
      memcpy(key.b + 32, &v32, 4);
      PrevoutInfo info{tx.outs[vout].value, nullptr, 0};
      out_script(tx.outs[vout], &info.script, &info.script_len);
      map[key] = info;
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Pass 0: walk tx structure, return tx count parsed and the item-capacity
// upper bound for txx_extract (1 per input; m*(n-m+1) candidates for a
// multisig template input).  tx_count == -1 parses to end of buffer.
// Returns number of txs, or -1 on malformed data.
long txx_scan(const uint8_t *data, long len, long tx_count,
              long *capacity_out) {
  Cursor c{data, data + len};
  long txs = 0;
  long capacity = 0;
  while (c.ok && (tx_count < 0 ? c.remaining() > 0 : txs < tx_count)) {
    TxSpan tx;
    if (!parse_tx(c, tx, /*compute_txid=*/false)) return -1;
    for (const InSpan &in : tx.ins) {
      InTemplate t;
      classify_input(in, t);
      capacity += t.kind == InTemplate::MULTISIG
                      ? long(t.ms.m) * (t.ms.n - t.ms.m + 1)
                      : 1;
    }
    ++txs;
  }
  // exact consumption: trailing bytes after tx_count txs are malformed
  // (mirror of wire.LazyBlock/LazyTx, which raise on trailing bytes)
  if (tx_count >= 0 && (txs != tx_count || c.remaining() > 0)) return -1;
  if (capacity_out) *capacity_out = capacity;
  return txs;
}

// Per-input prevout listing for the embedder's amount oracle: one row per
// input in flat parse order (coinbase included, so indices align with
// txx_extract's ext_amounts), carrying the prevout txid+vout and whether
// the input could consume a BIP143 amount (bch: every non-coinbase input;
// otherwise any input with a >=2-item witness — mirror of
// txverify.wants_amount).  Lets block ingest resolve amounts through
// NodeConfig.prevout_lookup without ever parsing txs in Python.
// Returns total input count, or -1 malformed / -2 capacity exceeded.
long txx_prevouts(const uint8_t *data, long len, long tx_count, int bch,
                  long capacity, uint8_t *txids32, int64_t *vouts,
                  uint8_t *wants) {
  Cursor c{data, data + len};
  long n = 0, flat = 0;
  static const uint8_t ZERO_TXID[32] = {0};
  while (c.ok && (tx_count < 0 ? c.remaining() > 0 : n < tx_count)) {
    TxSpan tx;
    if (!parse_tx(c, tx, /*compute_txid=*/false)) return -1;
    // tx-LEVEL witness gate (mirror of txverify.wants_amount): a taproot
    // keypath input digests EVERY input's amount+script, so any witness
    // in the tx makes all of its inputs worth a lookup; a single-push
    // scriptSig (bare-P2PK shape) wants its own prevout script too
    bool tx_has_wit = false;
    for (const InSpan &in : tx.ins) tx_has_wit |= in.wit_count >= 1;
    for (const InSpan &in : tx.ins) {
      if (flat >= capacity) return -2;
      memcpy(txids32 + flat * 32, in.prevout, 32);
      uint32_t vout;  // wire is little-endian; so is every target we build on
      memcpy(&vout, in.prevout + 32, 4);
      // int64 out: a vout >= 2^31 (junk or hostile) must reach the Python
      // prevout_lookup as the true unsigned value, not a negative int
      vouts[flat] = int64_t(vout);
      bool cb = memcmp(in.prevout, ZERO_TXID, 32) == 0;
      wants[flat] =
          (!cb && (bch || tx_has_wit || single_push_script_sig(in))) ? 1 : 0;
      ++flat;
    }
    ++n;
  }
  if (tx_count >= 0 && (n != tx_count || c.remaining() > 0)) return -1;
  return flat;
}

// Extract verifiable signature items from `tx_count` serialized txs.
//
//   flags bit 0: BCH network (FORKID hashtype selects the BIP143-style digest
//                for legacy inputs; amounts required for those)
//   flags bit 1: build and consult the intra-block prevout amount map
//                (block ingest: in-block spends resolve without a UTXO set)
//   ext_amounts: optional per-input amounts, flattened across txs in parse
//                order, -1 = unknown; consulted after the intra-block map
//                (mirror of node._verify_txs's block_outs -> prevout_lookup
//                precedence).  NULL = none.
//
// Per-item outputs (capacity rows each): z/px/py/r/s are 32-byte big-endian
// rows; present[i]=0 marks an auto-invalid item (undecodable pubkey or
// unparseable multisig sig).  item_sig/item_key/item_nsigs/item_nkeys
// locate multisig candidates (0/0/1/1 for single-sig items) — mirror of
// SigItem's candidate fields; combine per-signature verdicts with
// txverify.msig_match.
// Per-tx outputs (tx_count rows): txids (32B), tx_n_inputs, tx_extracted
// (INPUTS extracted), tx_items (device items), tx_sigs (signatures),
// tx_coinbase, tx_unsupported.
//
// Returns the item count, or -1 malformed data / -2 capacity exceeded.
long txx_extract(const uint8_t *data, long len, long tx_count, int flags,
                 const int64_t *ext_amounts, long n_ext, long capacity,
                 uint8_t *z, uint8_t *px, uint8_t *py, uint8_t *r, uint8_t *s,
                 uint8_t *present, int32_t *item_tx, int32_t *item_input,
                 int32_t *item_sig, int32_t *item_key, int32_t *item_nsigs,
                 int32_t *item_nkeys,
                 uint8_t *txids, int32_t *tx_n_inputs, int32_t *tx_extracted,
                 int32_t *tx_items, int32_t *tx_sigs,
                 int32_t *tx_coinbase, int32_t *tx_unsupported);

// Handle API: parse once, then run prevout listing and extraction (and any
// retries) over the SAME parsed spans — block ingest with the amount
// oracle previously parsed the region three times (scan for capacity,
// prevouts, extract).  The handle owns a copy of the wire bytes so spans
// stay valid independent of the caller's buffer lifetime.
struct TxxHandle {
  std::vector<uint8_t> data;
  std::vector<TxSpan> txs;
  long capacity = 0;  // candidate item bound
  long inputs = 0;    // total input count (ext_amounts row count)
  // Whole-region intra-block prevout map, built at most once
  // (txx_build_intra_h) and read-only afterwards — the seam that lets
  // tx-range shard extractions run concurrently on worker threads.
  PrevoutMap intra;
  bool intra_built = false;
};

void *txx_parse(const uint8_t *data, long len, long tx_count) {
  TxxHandle *h = new TxxHandle;
  h->data.assign(data, data + len);
  h->txs.reserve(tx_count > 0 ? size_t(tx_count) : 16);
  Cursor c{h->data.data(), h->data.data() + len};
  long n = 0;
  while (c.ok && (tx_count < 0 ? c.remaining() > 0 : n < tx_count)) {
    h->txs.emplace_back();
    if (!parse_tx(c, h->txs.back(), /*compute_txid=*/true)) {
      delete h;
      return nullptr;
    }
    ++n;
  }
  if (tx_count >= 0 && (n != tx_count || c.remaining() > 0)) {
    // exact consumption: trailing bytes after tx_count txs are malformed
    // (mirror of wire.LazyBlock/LazyTx, which raise on trailing bytes)
    delete h;
    return nullptr;
  }
  for (const TxSpan &tx : h->txs) {
    for (const InSpan &in : tx.ins) {
      InTemplate t;
      classify_input(in, t);
      h->capacity += t.kind == InTemplate::MULTISIG
                         ? long(t.ms.m) * (t.ms.n - t.ms.m + 1)
                         : 1;
      ++h->inputs;
    }
  }
  return h;
}

void txx_parse_free(void *h) { delete static_cast<TxxHandle *>(h); }

long txx_parsed_txs(void *h) {
  return long(static_cast<TxxHandle *>(h)->txs.size());
}
long txx_parsed_capacity(void *h) {
  return static_cast<TxxHandle *>(h)->capacity;
}
long txx_parsed_inputs(void *h) {
  return static_cast<TxxHandle *>(h)->inputs;
}

long txx_prevouts_h(void *hp, int bch, long capacity, uint8_t *txids32,
                    int64_t *vouts, uint8_t *wants) {
  TxxHandle *h = static_cast<TxxHandle *>(hp);
  long flat = 0;
  static const uint8_t ZERO_TXID[32] = {0};
  for (const TxSpan &tx : h->txs) {
    bool tx_has_wit = false;  // tx-level gate, see txx_prevouts
    for (const InSpan &in : tx.ins) tx_has_wit |= in.wit_count >= 1;
    for (const InSpan &in : tx.ins) {
      if (flat >= capacity) return -2;
      memcpy(txids32 + flat * 32, in.prevout, 32);
      uint32_t vout;
      memcpy(&vout, in.prevout + 32, 4);
      vouts[flat] = int64_t(vout);
      bool cb = memcmp(in.prevout, ZERO_TXID, 32) == 0;
      wants[flat] =
          (!cb && (bch || tx_has_wit || single_push_script_sig(in))) ? 1 : 0;
      ++flat;
    }
  }
  return flat;
}

long txx_extract_h(void *hp, int flags, const int64_t *ext_amounts,
                   long n_ext, long capacity, uint8_t *z, uint8_t *px,
                   uint8_t *py, uint8_t *r, uint8_t *s, uint8_t *present,
                   int32_t *item_tx, int32_t *item_input, int32_t *item_sig,
                   int32_t *item_key, int32_t *item_nsigs,
                   int32_t *item_nkeys, uint8_t *txids,
                   int32_t *tx_n_inputs, int32_t *tx_extracted,
                   int32_t *tx_items, int32_t *tx_sigs, int32_t *tx_coinbase,
                   int32_t *tx_unsupported);

long txx_extract_h2(void *hp, int flags, const int64_t *ext_amounts,
                    long n_ext, const uint8_t *ext_scripts,
                    const int64_t *ext_script_off, long capacity, uint8_t *z,
                    uint8_t *px, uint8_t *py, uint8_t *r, uint8_t *s,
                    uint8_t *present, int32_t *item_tx, int32_t *item_input,
                    int32_t *item_sig, int32_t *item_key, int32_t *item_nsigs,
                    int32_t *item_nkeys, uint8_t *txids,
                    int32_t *tx_n_inputs, int32_t *tx_extracted,
                    int32_t *tx_items, int32_t *tx_sigs, int32_t *tx_coinbase,
                    int32_t *tx_unsupported);

// Legacy one-shot entry: parse + extract in one call.
long txx_extract(const uint8_t *data, long len, long tx_count, int flags,
                 const int64_t *ext_amounts, long n_ext, long capacity,
                 uint8_t *z, uint8_t *px, uint8_t *py, uint8_t *r, uint8_t *s,
                 uint8_t *present, int32_t *item_tx, int32_t *item_input,
                 int32_t *item_sig, int32_t *item_key, int32_t *item_nsigs,
                 int32_t *item_nkeys,
                 uint8_t *txids, int32_t *tx_n_inputs, int32_t *tx_extracted,
                 int32_t *tx_items, int32_t *tx_sigs,
                 int32_t *tx_coinbase, int32_t *tx_unsupported) {
  void *h = txx_parse(data, len, tx_count);
  if (h == nullptr) return -1;
  long out = txx_extract_h(h, flags, ext_amounts, n_ext, capacity, z, px, py,
                           r, s, present, item_tx, item_input, item_sig,
                           item_key, item_nsigs, item_nkeys, txids,
                           tx_n_inputs, tx_extracted, tx_items, tx_sigs,
                           tx_coinbase, tx_unsupported);
  txx_parse_free(h);
  return out;
}

// Back-compat shim: extraction without prevout scripts (no taproot).
long txx_extract_h(void *hp, int flags, const int64_t *ext_amounts,
                   long n_ext, long capacity, uint8_t *z, uint8_t *px,
                   uint8_t *py, uint8_t *r, uint8_t *s, uint8_t *present,
                   int32_t *item_tx, int32_t *item_input, int32_t *item_sig,
                   int32_t *item_key, int32_t *item_nsigs,
                   int32_t *item_nkeys, uint8_t *txids,
                   int32_t *tx_n_inputs, int32_t *tx_extracted,
                   int32_t *tx_items, int32_t *tx_sigs, int32_t *tx_coinbase,
                   int32_t *tx_unsupported) {
  return txx_extract_h2(hp, flags, ext_amounts, n_ext, nullptr, nullptr,
                        capacity, z, px, py, r, s, present, item_tx,
                        item_input, item_sig, item_key, item_nsigs,
                        item_nkeys, txids, tx_n_inputs, tx_extracted,
                        tx_items, tx_sigs, tx_coinbase, tx_unsupported);
}

// Extraction body over an already-parsed handle.
//
// ext_scripts/ext_script_off extend the external prevout oracle with
// scriptPubKeys (VERDICT r4 item 3 — BIP341 digests sign over every
// input's amount AND script): ext_script_off has n_ext+1 entries; row i's
// script is ext_scripts[off[i]:off[i+1]], empty = unknown.  Rows align
// with ext_amounts (flat input order).  NULL = no scripts (no taproot
// extraction).
// Extraction body over a parsed handle, restricted to txs [tx_lo, tx_hi).
//
// The ext_amounts/ext_scripts oracle rows are RANGE-RELATIVE: row 0 is the
// first input of tx_lo, in flat parse order (the Python binding slices the
// whole-region rows with the tx-layout offsets).  Per-tx output arrays are
// sized/indexed for the range (row 0 = tx_lo) and item_tx is range-relative
// too, so a shard's RawSigItems is self-contained.
//
// Intra-map precedence: the handle's shared map (txx_build_intra_h) when
// built, else — one-shot back-compat — a local map over the whole region.
// Range callers MUST build the shared map first: ranges are extracted on
// concurrent worker threads and only the pre-built map is read-only.
static long extract_body(TxxHandle *h, int flags, const int64_t *ext_amounts,
                         long n_ext, const uint8_t *ext_scripts,
                         const int64_t *ext_script_off, long tx_lo, long tx_hi,
                         long capacity, uint8_t *z,
                         uint8_t *px, uint8_t *py, uint8_t *r, uint8_t *s,
                         uint8_t *present, int32_t *item_tx, int32_t *item_input,
                         int32_t *item_sig, int32_t *item_key, int32_t *item_nsigs,
                         int32_t *item_nkeys, uint8_t *txids,
                         int32_t *tx_n_inputs, int32_t *tx_extracted,
                         int32_t *tx_items, int32_t *tx_sigs, int32_t *tx_coinbase,
                         int32_t *tx_unsupported) {
  std::vector<TxSpan> &txs = h->txs;
  if (tx_lo < 0 || tx_hi > long(txs.size()) || tx_lo > tx_hi) return -1;
  bool bch = (flags & 1) != 0;
  bool intra = (flags & 2) != 0;
  PrevoutMap local_map;
  const PrevoutMap *prevout_map = nullptr;
  if (intra) {
    if (h->intra_built) {
      prevout_map = &h->intra;
    } else {
      build_prevout_map(txs, local_map);
      prevout_map = &local_map;
    }
  }

  // Resolve one input's prevout (amount, script): intra-block map first,
  // then the external oracle rows.  Returns a bitmask: 1 amount, 2 script.
  auto resolve = [&](const InSpan &in, long flat, int64_t *amt,
                     const uint8_t **scr, uint32_t *slen) -> int {
    int got = 0;
    if (intra) {
      OutpointKey key;
      memcpy(key.b, in.prevout, 36);
      auto it = prevout_map->find(key);
      if (it != prevout_map->end()) {
        *amt = it->second.value;
        got |= 1;
        if (it->second.script != nullptr) {
          *scr = it->second.script;
          *slen = it->second.script_len;
          got |= 2;
        }
      }
    }
    if (!(got & 1) && ext_amounts != nullptr && flat < n_ext &&
        ext_amounts[flat] >= 0) {
      *amt = ext_amounts[flat];
      got |= 1;
    }
    if (!(got & 2) && ext_scripts != nullptr && ext_script_off != nullptr &&
        flat < n_ext && ext_script_off[flat + 1] > ext_script_off[flat]) {
      *scr = ext_scripts + ext_script_off[flat];
      *slen = uint32_t(ext_script_off[flat + 1] - ext_script_off[flat]);
      got |= 2;
    }
    return got;
  };

  // pass 2: extract items
  static const uint8_t ZERO_TXID[32] = {0};
  std::vector<uint8_t> scratch;
  scratch.reserve(4096);
  PubkeyCache pubcache;   // SEC1 decode results, keyed by raw blob
  PubkeyCache liftcache;  // x-only lift results, keyed by x32 — separate
                          // object, so no cross-lane key collisions exist
  long item = 0;
  long flat_input = 0;  // RANGE-RELATIVE index into ext_amounts/ext_script_off
  for (size_t ti = size_t(tx_lo); ti < size_t(tx_hi); ++ti) {
    size_t oti = ti - size_t(tx_lo);  // range-relative output row
    TxSpan &tx = txs[ti];
    memcpy(txids + oti * 32, tx.txid, 32);
    int32_t n_inputs = 0, extracted = 0, coinbase = 0, unsupported = 0;
    int32_t sigs = 0;
    long tx_item_start = item;
    long tx_flat_base = flat_input;
    TapPrevouts tap;      // whole-tx prevout rows, built on first taproot use
    TapTxHashes taphash;  // per-tx BIP341 hash cache
    for (size_t idx = 0; idx < tx.ins.size(); ++idx, ++flat_input) {
      const InSpan &in = tx.ins[idx];
      ++n_inputs;
      if (memcmp(in.prevout, ZERO_TXID, 32) == 0) {
        ++coinbase;
        continue;
      }

      // prevout resolution (shared by every template; scripts matter only
      // for taproot detection + BIP341)
      int64_t amount = 0;
      const uint8_t *pscript = nullptr;
      uint32_t pscript_len = 0;
      int got = resolve(in, flat_input, &amount, &pscript, &pscript_len);
      bool have_amount = (got & 1) != 0;

      if (!bch && (got & 2) && is_p2tr_script(pscript, pscript_len)) {
        // Taproot spend (mirror of txverify._taproot_item): KEYPATH
        // witness = [sig] (+annex); SCRIPT path with the canonical
        // single-key tapscript = [sig, <32B key> OP_CHECKSIG, control]
        // (+annex).  Other tapscripts are unsupported — this is a
        // signature pre-verifier, not a tapscript interpreter.
        uint32_t wn = in.wit_count;
        const uint8_t *annex = nullptr;
        size_t annex_len = 0;
        if (wn > MAX_WIT_SPANS) {
          ++unsupported;  // can't even see the trailing spans: script path
          continue;
        }
        if (wn >= 2 && in.wit_len[wn - 1] >= 1 &&
            in.wit[wn - 1][0] == 0x50) {
          annex = in.wit[wn - 1];
          annex_len = in.wit_len[wn - 1];
          --wn;
        }
        uint8_t leaf_buf[32];
        const uint8_t *leaf_hash = nullptr;
        const uint8_t *key_ptr;  // 32-byte x-only key for this spend
        if (wn == 1) {
          key_ptr = pscript + 2;  // keypath: the output key
        } else if (wn == 3 &&
                   is_single_key_tapscript(in.wit[1], in.wit_len[1]) &&
                   valid_control_block(in.wit[2], in.wit_len[2])) {
          key_ptr = in.wit[1] + 1;  // the leaf's key
          Sha256 lh;
          tagged_hash_init(lh, tap_leaf_tag());
          uint8_t hdr[2] = {uint8_t(in.wit[2][0] & 0xFE),
                            uint8_t(in.wit_len[1])};
          lh.update(hdr, 2);  // leaf version ∥ varstr length (34 < 0xFD)
          lh.update(in.wit[1], in.wit_len[1]);
          lh.final(leaf_buf);
          leaf_hash = leaf_buf;
        } else {
          ++unsupported;
          continue;
        }
        const uint8_t *sig = in.wit[0];
        uint32_t sig_len = in.wit_len[0];
        // Consensus-invalid shapes emit an AUTO-INVALID item (present=0):
        // the spend is invalid, not unsupported.
        auto emit_invalid = [&](const uint8_t *rb, const uint8_t *sb) -> bool {
          if (item >= capacity) return false;
          memset(z + item * 32, 0, 32);
          memset(px + item * 32, 0, 32);
          memset(py + item * 32, 0, 32);
          if (rb != nullptr) memcpy(r + item * 32, rb, 32);
          else memset(r + item * 32, 0, 32);
          if (sb != nullptr) memcpy(s + item * 32, sb, 32);
          else memset(s + item * 32, 0, 32);
          present[item] = 0;
          item_tx[item] = int32_t(oti);
          item_input[item] = int32_t(idx);
          item_sig[item] = 0;
          item_key[item] = 0;
          item_nsigs[item] = 1;
          item_nkeys[item] = 1;
          ++item;
          ++extracted;
          ++sigs;
          return true;
        };
        int hashtype;
        if (sig_len == 64) {
          hashtype = 0x00;
        } else if (sig_len == 65) {
          hashtype = sig[64];
          if (hashtype == 0x00) {
            // 65-byte sig must carry an explicit type (zero r/s, mirror
            // of txverify's bare invalid())
            if (!emit_invalid(nullptr, nullptr)) return -2;
            continue;
          }
        } else {
          if (!emit_invalid(nullptr, nullptr)) return -2;
          continue;
        }
        // ACP bit decides WHICH prevouts are required even when the
        // hash_type is invalid (parity with txverify._taproot_item's
        // `need` computation; the invalid type then fails in the digest)
        bool acp = (hashtype & SIGHASH_ANYONECANPAY) != 0;
        if (!tap.built) {
          size_t n_in = tx.ins.size();
          tap.amounts.assign(n_in, 0);
          tap.scripts.assign(n_in, nullptr);
          tap.script_lens.assign(n_in, 0);
          tap.have.assign(n_in, false);
          for (size_t i = 0; i < n_in; ++i) {
            int64_t a = 0;
            const uint8_t *sc = nullptr;
            uint32_t sl = 0;
            int g = resolve(tx.ins[i], tx_flat_base + long(i), &a, &sc, &sl);
            if ((g & 3) == 3) {
              tap.amounts[i] = a;
              tap.scripts[i] = sc;
              tap.script_lens[i] = sl;
              tap.have[i] = true;
            }
          }
          tap.built = true;
        }
        bool have_prevouts = acp ? bool(tap.have[idx])
                                 : std::all_of(tap.have.begin(),
                                               tap.have.end(),
                                               [](bool b) { return b; });
        if (!have_prevouts) {
          ++unsupported;  // digest uncomputable: missing prevout info
          continue;
        }
        uint8_t digest[32];
        if (!bip341_sighash(tx, idx, hashtype, annex, annex_len, tap,
                            taphash, scratch, digest, leaf_hash)) {
          if (!emit_invalid(sig, sig + 32)) return -2;
          continue;
        }
        uint8_t pxb[32], pyb[32];
        if (!lift_x_cached(liftcache, key_ptr, pxb, pyb)) {
          // off-curve key: invalid spend
          if (!emit_invalid(sig, sig + 32)) return -2;
          continue;
        }
        if (item >= capacity) return -2;
        // challenge e = tagged(BIP0340/challenge, r ∥ px ∥ m) mod n —
        // extraction precomputes it, like the BCH Schnorr lane
        uint8_t e32[32];
        Sha256 h;
        tagged_hash_init(h, bip340_challenge_tag());
        h.update(sig, 32);       // r
        h.update(pxb, 32);       // x-only pubkey
        h.update(digest, 32);    // m
        h.final(e32);
        reduce_mod_n(e32);
        memcpy(z + item * 32, e32, 32);
        memcpy(px + item * 32, pxb, 32);
        memcpy(py + item * 32, pyb, 32);
        memcpy(r + item * 32, sig, 32);
        memcpy(s + item * 32, sig + 32, 32);
        present[item] = 3;
        item_tx[item] = int32_t(oti);
        item_input[item] = int32_t(idx);
        item_sig[item] = 0;
        item_key[item] = 0;
        item_nsigs[item] = 1;
        item_nkeys[item] = 1;
        ++item;
        ++extracted;
        ++sigs;
        continue;
      }

      InTemplate t;
      classify_input(in, t);
      if (t.kind == InTemplate::UNSUPPORTED && (got & 2) &&
          in.wit_count == 0 && single_push_script_sig(in)) {
        // bare P2PK: scriptSig = <sig>, key in the prevout script — only
        // the oracle's script makes this classifiable
        size_t klen;
        const uint8_t *key = is_p2pk_script(pscript, pscript_len, &klen);
        if (key != nullptr) {
          t.kind = InTemplate::SINGLE;
          t.sig = in.script + 1;
          t.sig_len = in.script_len - 1;
          t.pub = key;
          t.pub_len = klen;
          t.sc = pscript;
          t.sc_len = pscript_len;
        }
      }
      if (t.kind == InTemplate::UNSUPPORTED) {
        ++unsupported;
        continue;
      }

      if (t.kind == InTemplate::SINGLE) {
        if (t.sig_len < 9) {
          ++unsupported;
          continue;
        }
        int hashtype = t.sig[t.sig_len - 1];
        // BCH consensus: a 65-byte signature blob (64 + hashtype) IS
        // Schnorr (2019-05 upgrade) — r ∥ s raw, no DER.
        bool is_schnorr = bch && t.sig_len == 65;
        uint8_t rbuf[32], sbuf[32];
        if (is_schnorr) {
          memcpy(rbuf, t.sig, 32);
          memcpy(sbuf, t.sig + 32, 32);
        } else if (!parse_der(t.sig, t.sig_len - 1, rbuf, sbuf)) {
          ++unsupported;
          continue;
        }
        // script_code: the template's own script when set (P2WSH
        // single-key witness script, bare P2PK prevout script), else the
        // P2PKH template over hash160(pubkey)
        uint8_t p2pkh_code[25];
        const uint8_t *script_code = t.sc;
        size_t sc_len = t.sc_len;
        if (script_code == nullptr) {
          p2pkh_code[0] = 0x76; p2pkh_code[1] = 0xA9; p2pkh_code[2] = 0x14;
          hash160(t.pub, t.pub_len, p2pkh_code + 3);
          p2pkh_code[23] = 0x88; p2pkh_code[24] = 0xAC;
          script_code = p2pkh_code;
          sc_len = 25;
        }
        uint8_t digest[32];
        if (t.segwit || (bch && (hashtype & SIGHASH_FORKID))) {
          if (!have_amount) {
            ++unsupported;
            continue;
          }
          bip143_sighash(tx, idx, script_code, sc_len, amount, hashtype,
                         scratch, digest);
        } else {
          legacy_sighash(tx, idx, script_code, sc_len, hashtype, scratch,
                         digest);
        }
        if (item >= capacity) return -2;
        memcpy(r + item * 32, rbuf, 32);
        memcpy(s + item * 32, sbuf, 32);
        if (is_schnorr) {
          // challenge e = SHA256(r ∥ P_compressed ∥ m) mod n, hashed over
          // the UNREDUCED sighash (mirror of ecdsa_cpu.schnorr_challenge);
          // undecodable pubkey -> auto-invalid row with z = 0.
          uint8_t pxb[32], pyb[32];
          bool okp = decode_pubkey_cached(pubcache, t.pub, t.pub_len, pxb,
                                          pyb);
          if (okp) {
            uint8_t pre[97];
            memcpy(pre, rbuf, 32);
            pre[32] = uint8_t(0x02 | (pyb[31] & 1));
            memcpy(pre + 33, pxb, 32);
            memcpy(pre + 65, digest, 32);
            uint8_t e32[32];
            Sha256 h;
            h.update(pre, 97);
            h.final(e32);
            reduce_mod_n(e32);
            memcpy(z + item * 32, e32, 32);
            memcpy(px + item * 32, pxb, 32);
            memcpy(py + item * 32, pyb, 32);
            present[item] = 2;
          } else {
            memset(z + item * 32, 0, 32);
            memset(px + item * 32, 0, 32);
            memset(py + item * 32, 0, 32);
            present[item] = 0;
          }
        } else {
          reduce_mod_n(digest);
          memcpy(z + item * 32, digest, 32);
          present[item] =
              decode_pubkey_cached(pubcache, t.pub, t.pub_len, px + item * 32,
                                   py + item * 32)
                  ? 1
                  : 0;
          if (!present[item]) {
            memset(px + item * 32, 0, 32);
            memset(py + item * 32, 0, 32);
          }
        }
        item_tx[item] = int32_t(oti);
        item_input[item] = int32_t(idx);
        item_sig[item] = 0;
        item_key[item] = 0;
        item_nsigs[item] = 1;
        item_nkeys[item] = 1;
        ++item;
        ++extracted;
        ++sigs;
        continue;
      }

      // MULTISIG: emit m*(n-m+1) candidate (sig, key) pairs.  A missing
      // required amount mid-loop rolls the whole input back to unsupported
      // (same precedence as txverify._msig_items).
      int m = t.ms.m, n = t.ms.n;
      long input_start = item;
      // decode each key at most once per input
      uint8_t kx[16][32], ky[16][32];
      int kdec[16];
      for (int k = 0; k < 16; ++k) kdec[k] = -1;
      bool input_unsupported = false;
      for (int i = 0; i < m && !input_unsupported; ++i) {
        const uint8_t *sig_blob = t.sigs[i];
        size_t sig_len = t.sig_lens[i];
        uint8_t rbuf[32], sbuf[32], digest[32];
        bool have_sig = sig_len >= 9 &&
                        parse_der(sig_blob, sig_len - 1, rbuf, sbuf);
        if (have_sig) {
          int hashtype = sig_blob[sig_len - 1];
          if (t.segwit || (bch && (hashtype & SIGHASH_FORKID))) {
            if (!have_amount) {
              input_unsupported = true;
              break;
            }
            bip143_sighash(tx, idx, t.sc, t.sc_len, amount, hashtype, scratch,
                           digest);
          } else {
            legacy_sighash(tx, idx, t.sc, t.sc_len, hashtype, scratch, digest);
          }
          reduce_mod_n(digest);
        }
        for (int j = i; j <= n - m + i; ++j) {
          if (item >= capacity) return -2;
          if (!have_sig) {
            memset(z + item * 32, 0, 32);
            memset(r + item * 32, 0, 32);
            memset(s + item * 32, 0, 32);
            memset(px + item * 32, 0, 32);
            memset(py + item * 32, 0, 32);
            present[item] = 0;
          } else {
            memcpy(z + item * 32, digest, 32);
            memcpy(r + item * 32, rbuf, 32);
            memcpy(s + item * 32, sbuf, 32);
            if (kdec[j] < 0)
              kdec[j] = decode_pubkey_cached(pubcache, t.ms.keys[j],
                                             t.ms.key_len[j], kx[j], ky[j])
                            ? 1
                            : 0;
            present[item] = uint8_t(kdec[j]);
            if (kdec[j]) {
              memcpy(px + item * 32, kx[j], 32);
              memcpy(py + item * 32, ky[j], 32);
            } else {
              memset(px + item * 32, 0, 32);
              memset(py + item * 32, 0, 32);
            }
          }
          item_tx[item] = int32_t(oti);
          item_input[item] = int32_t(idx);
          item_sig[item] = i;
          item_key[item] = j;
          item_nsigs[item] = m;
          item_nkeys[item] = n;
          ++item;
        }
      }
      if (input_unsupported) {
        item = input_start;  // roll back any emitted candidates
        ++unsupported;
      } else {
        ++extracted;
        sigs += m;
      }
    }
    tx_n_inputs[oti] = n_inputs;
    tx_extracted[oti] = extracted;
    tx_items[oti] = int32_t(item - tx_item_start);
    tx_sigs[oti] = sigs;
    tx_coinbase[oti] = coinbase;
    tx_unsupported[oti] = unsupported;
  }
  return item;
}

long txx_extract_h2(void *hp, int flags, const int64_t *ext_amounts,
                    long n_ext, const uint8_t *ext_scripts,
                    const int64_t *ext_script_off, long capacity, uint8_t *z,
                    uint8_t *px, uint8_t *py, uint8_t *r, uint8_t *s,
                    uint8_t *present, int32_t *item_tx, int32_t *item_input,
                    int32_t *item_sig, int32_t *item_key, int32_t *item_nsigs,
                    int32_t *item_nkeys, uint8_t *txids,
                    int32_t *tx_n_inputs, int32_t *tx_extracted,
                    int32_t *tx_items, int32_t *tx_sigs, int32_t *tx_coinbase,
                    int32_t *tx_unsupported) {
  TxxHandle *h = static_cast<TxxHandle *>(hp);
  return extract_body(h, flags, ext_amounts, n_ext, ext_scripts,
                      ext_script_off, 0, long(h->txs.size()), capacity, z, px,
                      py, r, s, present, item_tx, item_input, item_sig,
                      item_key, item_nsigs, item_nkeys, txids, tx_n_inputs,
                      tx_extracted, tx_items, tx_sigs, tx_coinbase,
                      tx_unsupported);
}

// Build the handle's shared whole-region intra-block prevout map (at most
// once; idempotent).  MUST run before any txx_extract_range_h with the
// intra flag: ranges extract on concurrent threads and only the pre-built
// map is read-only.  Returns the map size.
long txx_build_intra_h(void *hp) {
  TxxHandle *h = static_cast<TxxHandle *>(hp);
  if (!h->intra_built) {
    build_prevout_map(h->txs, h->intra);
    h->intra_built = true;
  }
  return long(h->intra.size());
}

// Per-tx layout rows (n_txs each): input counts and candidate-item
// capacities — the Python side derives range capacities and the flat
// oracle-row offsets (cumsum) for tx-range sharding from these.
long txx_tx_layout_h(void *hp, int32_t *n_inputs, int32_t *capacity) {
  TxxHandle *h = static_cast<TxxHandle *>(hp);
  for (size_t ti = 0; ti < h->txs.size(); ++ti) {
    const TxSpan &tx = h->txs[ti];
    long cap = 0;
    for (const InSpan &in : tx.ins) {
      InTemplate t;
      classify_input(in, t);
      cap += t.kind == InTemplate::MULTISIG
                 ? long(t.ms.m) * (t.ms.n - t.ms.m + 1)
                 : 1;
    }
    n_inputs[ti] = int32_t(tx.ins.size());
    capacity[ti] = int32_t(cap);
  }
  return long(h->txs.size());
}

// Tx-range extraction over the shared handle (ISSUE 11): same result rows
// as txx_extract_h2 but only for txs [tx_lo, tx_hi), with range-relative
// oracle rows and output indices (see extract_body).  Thread-safe across
// DISJOINT ranges once txx_build_intra_h ran (or the intra flag is off).
long txx_extract_range_h(void *hp, int flags, const int64_t *ext_amounts,
                         long n_ext, const uint8_t *ext_scripts,
                         const int64_t *ext_script_off, long tx_lo, long tx_hi,
                         long capacity, uint8_t *z,
                         uint8_t *px, uint8_t *py, uint8_t *r, uint8_t *s,
                         uint8_t *present, int32_t *item_tx, int32_t *item_input,
                         int32_t *item_sig, int32_t *item_key,
                         int32_t *item_nsigs, int32_t *item_nkeys,
                         uint8_t *txids, int32_t *tx_n_inputs,
                         int32_t *tx_extracted, int32_t *tx_items,
                         int32_t *tx_sigs, int32_t *tx_coinbase,
                         int32_t *tx_unsupported) {
  return extract_body(static_cast<TxxHandle *>(hp), flags, ext_amounts, n_ext,
                      ext_scripts, ext_script_off, tx_lo, tx_hi, capacity, z,
                      px, py, r, s, present, item_tx, item_input, item_sig,
                      item_key, item_nsigs, item_nkeys, txids, tx_n_inputs,
                      tx_extracted, tx_items, tx_sigs, tx_coinbase,
                      tx_unsupported);
}

// ---------------------------------------------------------------------------
// Native UTXO block-connect (ISSUE 11): one pass over the parsed region
// emits the block's spend/create key-value delta as a ready-to-apply batch
// blob in the v1 record format (op u8, klen u32le, vlen u32le, key, value):
//
//   create: op=1, key = prefix ++ txid ++ vout_le32,
//           value = amount_le64 ++ scriptPubKey
//   spend:  op=2, key = prefix ++ prevout_txid ++ prevout_vout_le32
//
// Creates are emitted before spends per the WHOLE region and coinbase
// inputs spend nothing — exactly UtxoStore.apply_block's semantics, so the
// Python per-tx parse leaves block ingest entirely (node._apply_block_utxo).
// ---------------------------------------------------------------------------

// Exact byte size of the ops blob txx_utxo_ops_h would emit.
long txx_utxo_size_h(void *hp) {
  TxxHandle *h = static_cast<TxxHandle *>(hp);
  static const uint8_t ZERO_TXID[32] = {0};
  const long REC = 9, KEY = 1 + 32 + 4;
  long total = 0;
  for (const TxSpan &tx : h->txs) {
    for (const OutSpan &o : tx.outs) {
      const uint8_t *script = nullptr;
      uint32_t slen = 0;
      out_script(o, &script, &slen);
      total += REC + KEY + 8 + long(slen);
    }
    for (const InSpan &in : tx.ins) {
      if (memcmp(in.prevout, ZERO_TXID, 32) != 0) total += REC + KEY;
    }
  }
  return total;
}

// Emit the delta blob into `out` (capacity `cap` bytes).  `created` /
// `spent` receive the op counts.  Returns bytes written, or -2 when cap
// is too small (use txx_utxo_size_h).
long txx_utxo_ops_h(void *hp, uint8_t prefix, long cap, uint8_t *out,
                    long *created, long *spent) {
  TxxHandle *h = static_cast<TxxHandle *>(hp);
  static const uint8_t ZERO_TXID[32] = {0};
  long pos = 0, n_created = 0, n_spent = 0;
  auto put_hdr = [&](uint8_t op, uint32_t klen, uint32_t vlen) {
    out[pos] = op;
    memcpy(out + pos + 1, &klen, 4);  // little-endian on supported targets
    memcpy(out + pos + 5, &vlen, 4);
    pos += 9;
  };
  const uint32_t KEY = 1 + 32 + 4;
  for (const TxSpan &tx : h->txs) {
    for (size_t vout = 0; vout < tx.outs.size(); ++vout) {
      const OutSpan &o = tx.outs[vout];
      const uint8_t *script = nullptr;
      uint32_t slen = 0;
      out_script(o, &script, &slen);
      uint32_t vlen = 8 + slen;
      if (pos + 9 + long(KEY) + long(vlen) > cap) return -2;
      put_hdr(1, KEY, vlen);
      out[pos] = prefix;
      memcpy(out + pos + 1, tx.txid, 32);
      uint32_t v32 = uint32_t(vout);
      memcpy(out + pos + 33, &v32, 4);
      pos += KEY;
      uint64_t amt = uint64_t(o.value);
      memcpy(out + pos, &amt, 8);
      if (slen) memcpy(out + pos + 8, script, slen);
      pos += vlen;
      ++n_created;
    }
  }
  for (const TxSpan &tx : h->txs) {
    for (const InSpan &in : tx.ins) {
      if (memcmp(in.prevout, ZERO_TXID, 32) == 0) continue;
      if (pos + 9 + long(KEY) > cap) return -2;
      put_hdr(2, KEY, 0);
      out[pos] = prefix;
      memcpy(out + pos + 1, in.prevout, 36);  // txid ++ vout_le32 (wire order)
      pos += KEY;
      ++n_spent;
    }
  }
  if (created) *created = n_created;
  if (spent) *spent = n_spent;
  return pos;
}

// All parsed txids, row-major (n_txs x 32) — block connect and mempool
// confirmation need the txid list without a Python parse OR an extract.
long txx_txids_h(void *hp, uint8_t *out) {
  TxxHandle *h = static_cast<TxxHandle *>(hp);
  for (size_t ti = 0; ti < h->txs.size(); ++ti)
    memcpy(out + ti * 32, h->txs[ti].txid, 32);
  return long(h->txs.size());
}

}  // extern "C"
