// Native persistent KV store: the framework's analog of the reference's
// RocksDB dependency (reference package.yaml:32-33, used by the header
// chain at src/Haskoin/Node/Chain.hs:73-84,233-263,454-491).
//
// Design: append-only log + in-memory ordered index (std::map), replayed
// on open with torn-tail truncation, compacted when dead bytes dominate.
// The on-disk record format is the LEGACY v1 log:
// op(u8) klen(u32le) vlen(u32le) key value.  The Python LogKV engine
// (tpunode/store.py) now writes the crash-consistent v2 segmented format
// (CRC32 + sequence numbers + file headers, ISSUE 9); its v2 reader
// replays v1 files bit-identically, and the Python binding
// (tpunode/native.py) version-gates this engine — it refuses to open a
// directory holding v2 artifacts rather than serve a stale subset.
//
// Exposed as a C ABI for ctypes (tpunode/native.py).  Single-writer,
// like the reference's usage of RocksDB (one Chain actor owns the DB).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#ifdef _WIN32
#error "POSIX only"
#endif
#include <unistd.h>

namespace {

constexpr uint8_t OP_PUT = 1;
constexpr uint8_t OP_DEL = 2;
constexpr size_t REC_HDR = 9;  // 1 + 4 + 4

struct Store {
  std::string path;
  std::map<std::string, std::string> data;
  FILE* file = nullptr;
  uint64_t dead_bytes = 0;
  uint64_t live_bytes = 0;

  ~Store() {
    if (file) fclose(file);
  }

  void note_replace(const std::string& key) {
    auto it = data.find(key);
    if (it != data.end()) {
      uint64_t dead = REC_HDR + key.size() + it->second.size();
      dead_bytes += dead;
      live_bytes -= dead;
    }
  }

  static void put_rec(std::string& out, uint8_t op, const char* k,
                      uint32_t klen, const char* v, uint32_t vlen) {
    char hdr[REC_HDR];
    hdr[0] = static_cast<char>(op);
    memcpy(hdr + 1, &klen, 4);  // little-endian on every supported target
    memcpy(hdr + 5, &vlen, 4);
    out.append(hdr, REC_HDR);
    out.append(k, klen);
    if (vlen) out.append(v, vlen);
  }

  bool replay() {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return true;  // fresh store
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    std::vector<char> raw(static_cast<size_t>(sz));
    if (sz && fread(raw.data(), 1, raw.size(), f) != raw.size()) {
      fclose(f);
      return false;
    }
    fclose(f);
    size_t pos = 0, good = 0;
    while (pos + REC_HDR <= raw.size()) {
      uint8_t op = static_cast<uint8_t>(raw[pos]);
      uint32_t klen, vlen;
      memcpy(&klen, raw.data() + pos + 1, 4);
      memcpy(&vlen, raw.data() + pos + 5, 4);
      size_t end = pos + REC_HDR + static_cast<size_t>(klen) + vlen;
      if (end > raw.size() || (op != OP_PUT && op != OP_DEL)) break;
      std::string key(raw.data() + pos + REC_HDR, klen);
      note_replace(key);
      if (op == OP_PUT) {
        data[key] = std::string(raw.data() + pos + REC_HDR + klen, vlen);
        live_bytes += end - pos;
      } else {
        data.erase(key);
        dead_bytes += end - pos;
      }
      pos = end;
      good = pos;
    }
    if (good < raw.size()) {  // torn/corrupt tail: truncate it away
      if (truncate(path.c_str(), static_cast<off_t>(good)) != 0) return false;
    }
    return true;
  }

  bool commit(const std::string& blob, bool do_fsync) {
    if (fwrite(blob.data(), 1, blob.size(), file) != blob.size()) return false;
    if (fflush(file) != 0) return false;
    if (do_fsync && fsync(fileno(file)) != 0) return false;
    if (dead_bytes >= (1u << 20) && dead_bytes >= 3 * live_bytes)
      compact();  // opportunistic: the write above is already durable, and
                  // a failed compaction reopens the log and keeps going
    return file != nullptr;
  }

  bool compact() {
    // The old log handle is only closed after the new file is fully
    // written; on ANY failure the handle is re-opened so the store stays
    // writable (a failed compaction must degrade, not poison the Store).
    std::string tmp = path + ".compact";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    std::string blob;
    for (auto& [k, v] : data) {
      blob.clear();
      put_rec(blob, OP_PUT, k.data(), static_cast<uint32_t>(k.size()),
              v.data(), static_cast<uint32_t>(v.size()));
      if (fwrite(blob.data(), 1, blob.size(), f) != blob.size()) {
        fclose(f);
        remove(tmp.c_str());
        return false;
      }
    }
    if (fflush(f) != 0 || fsync(fileno(f)) != 0) {
      fclose(f);
      remove(tmp.c_str());
      return false;
    }
    fclose(f);
    fclose(file);
    file = nullptr;
    bool ok = rename(tmp.c_str(), path.c_str()) == 0;
    file = fopen(path.c_str(), "ab");  // reopen whichever file now exists
    if (!ok || !file) return false;
    dead_bytes = 0;
    live_bytes = 0;
    for (auto& [k, v] : data) live_bytes += REC_HDR + k.size() + v.size();
    return true;
  }
};

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  auto* s = new Store();
  s->path = path;
  if (!s->replay()) {
    delete s;
    return nullptr;
  }
  s->file = fopen(path, "ab");
  if (!s->file) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) { delete static_cast<Store*>(h); }

// 1 = found (out/outlen set, free with kv_buf_free), 0 = missing.
int kv_get(void* h, const char* key, uint32_t klen, char** out,
           uint64_t* outlen) {
  auto* s = static_cast<Store*>(h);
  auto it = s->data.find(std::string(key, klen));
  if (it == s->data.end()) return 0;
  *outlen = it->second.size();
  *out = static_cast<char*>(malloc(it->second.size() ? it->second.size() : 1));
  memcpy(*out, it->second.data(), it->second.size());
  return 1;
}

// blob = concatenated records in the on-disk format. 0 = ok.
int kv_write_batch(void* h, const char* blob, uint64_t len, int do_fsync) {
  auto* s = static_cast<Store*>(h);
  size_t pos = 0;
  std::string out;
  out.reserve(len);
  while (pos + REC_HDR <= len) {
    uint8_t op = static_cast<uint8_t>(blob[pos]);
    uint32_t klen, vlen;
    memcpy(&klen, blob + pos + 1, 4);
    memcpy(&vlen, blob + pos + 5, 4);
    size_t end = pos + REC_HDR + static_cast<size_t>(klen) + vlen;
    if (end > len || (op != OP_PUT && op != OP_DEL)) return -1;
    std::string key(blob + pos + REC_HDR, klen);
    s->note_replace(key);
    if (op == OP_PUT) {
      s->data[key] = std::string(blob + pos + REC_HDR + klen, vlen);
      s->live_bytes += end - pos;
    } else {
      s->data.erase(key);
      s->dead_bytes += end - pos;
    }
    pos = end;
  }
  if (pos != len) return -1;
  return s->commit(std::string(blob, len), do_fsync != 0) ? 0 : -2;
}

// Serialize every (key, value) with key starting with prefix, in key order,
// as klen(u32le) vlen(u32le) key value records.  Free with kv_buf_free.
int kv_scan_prefix(void* h, const char* prefix, uint32_t plen, char** out,
                   uint64_t* outlen) {
  auto* s = static_cast<Store*>(h);
  std::string pfx(prefix, plen);
  std::string buf;
  for (auto it = s->data.lower_bound(pfx); it != s->data.end(); ++it) {
    if (it->first.compare(0, pfx.size(), pfx) != 0) break;
    uint32_t klen = static_cast<uint32_t>(it->first.size());
    uint32_t vlen = static_cast<uint32_t>(it->second.size());
    char hdr[8];
    memcpy(hdr, &klen, 4);
    memcpy(hdr + 4, &vlen, 4);
    buf.append(hdr, 8);
    buf.append(it->first);
    buf.append(it->second);
  }
  *outlen = buf.size();
  *out = static_cast<char*>(malloc(buf.size() ? buf.size() : 1));
  memcpy(*out, buf.data(), buf.size());
  return 0;
}

int kv_compact(void* h) {
  return static_cast<Store*>(h)->compact() ? 0 : -1;
}

uint64_t kv_count(void* h) { return static_cast<Store*>(h)->data.size(); }

void kv_buf_free(char* p) { free(p); }

}  // extern "C"
