// Native persistent KV store: the framework's analog of the reference's
// RocksDB dependency (reference package.yaml:32-33, used by the header
// chain at src/Haskoin/Node/Chain.hs:73-84,233-263,454-491).
//
// Design: append-only log + in-memory ordered index (std::map), replayed
// on open with torn-tail truncation, compacted when dead bytes dominate.
//
// Two on-disk modes, decided at open time (ISSUE 11):
//
//  * LEGACY v1: a single file of op(u8) klen(u32le) vlen(u32le) key value
//    records — kept for paths with no v2 artifacts, bit-compatible with
//    what this engine always wrote (the Python v2 reader replays it).
//  * v2 SEGMENTED (the format the Python LogKV writes, ISSUE 9): a base
//    snapshot/legacy file plus `<base>.NNNNNNNN.seg` segment files, each
//    opening with a TPK2 header (magic, version u16, kind u16, seq u64)
//    and carrying crc32(u32) seq(u32) op(u8) klen(u32) vlen(u32) records
//    where the CRC covers everything after itself.  This engine now
//    REPLAYS that format (CRC + per-segment sequence validated, torn
//    tails of the last file truncated) and APPENDS to it by opening a
//    fresh segment of its own — so `open_store(path, engine="native")`
//    serves the directory the node actually writes, and the Python
//    reader replays the result bit-identically (pinned by
//    tests/test_native_v2.py).
//
// Recovery division of labor: a torn tail of the LAST file is truncated
// here exactly like the Python reader's quiet path, but mid-log damage
// (a sealed file failing CRC/sequence checks, or unparseable bytes with
// valid successor records) REFUSES to open — quarantining salvage is
// LogKV's richer recovery path, and silently serving a prefix of acked
// data is the one thing a fallback engine must never do.
//
// Exposed as a C ABI for ctypes (tpunode/native.py).  Single-writer,
// like the reference's usage of RocksDB (one Chain actor owns the DB).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <algorithm>
#include <map>
#include <string>
#include <vector>

#ifdef _WIN32
#error "POSIX only"
#endif
#include <dirent.h>
#include <fcntl.h>
#include <unistd.h>

namespace {

constexpr uint8_t OP_PUT = 1;
constexpr uint8_t OP_DEL = 2;
constexpr size_t REC_HDR = 9;        // v1: 1 + 4 + 4
constexpr size_t REC_V2_HDR = 17;    // crc(4) seq(4) op(1) klen(4) vlen(4)
constexpr size_t FILE_HDR = 16;      // magic(4) version(2) kind(2) seq(8)
constexpr uint16_t FMT_VERSION = 2;
constexpr uint16_t KIND_LOG = 0;
constexpr uint16_t KIND_SNAPSHOT = 1;
const char MAGIC[4] = {'T', 'P', 'K', '2'};
constexpr uint64_t SEG_LIMIT = 64ull << 20;  // rotation size, LogKV default

// zlib-compatible CRC-32 (polynomial 0xEDB88320), table-driven.
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};

uint32_t crc32(const uint8_t *p, size_t n) {
  static const Crc32Table tab;
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = tab.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

void put_u32(uint8_t *p, uint32_t v) { memcpy(p, &v, 4); }  // LE targets only
void put_u64(uint8_t *p, uint64_t v) { memcpy(p, &v, 8); }

bool fsync_dir(const std::string &dir) {
  int fd = open(dir.empty() ? "." : dir.c_str(), O_RDONLY);
  if (fd < 0) return false;
  bool ok = fsync(fd) == 0;
  close(fd);
  return ok;
}

std::string dirname_of(const std::string &path) {
  size_t cut = path.find_last_of('/');
  return cut == std::string::npos ? std::string(".") : path.substr(0, cut);
}

std::string basename_of(const std::string &path) {
  size_t cut = path.find_last_of('/');
  return cut == std::string::npos ? path : path.substr(cut + 1);
}

std::string seg_path(const std::string &base, uint64_t seq) {
  char buf[32];
  snprintf(buf, sizeof(buf), ".%08llu.seg", (unsigned long long)seq);
  return base + buf;
}

// (seq, path) for every segment of `base`, ascending.
std::vector<std::pair<uint64_t, std::string>> list_segments(
    const std::string &base) {
  std::vector<std::pair<uint64_t, std::string>> out;
  std::string dir = dirname_of(base);
  std::string prefix = basename_of(base) + ".";
  DIR *d = opendir(dir.c_str());
  if (!d) return out;
  while (dirent *e = readdir(d)) {
    std::string name = e->d_name;
    if (name.size() <= prefix.size() + 4) continue;
    if (name.compare(0, prefix.size(), prefix) != 0) continue;
    if (name.compare(name.size() - 4, 4, ".seg") != 0) continue;
    std::string mid = name.substr(prefix.size(), name.size() - prefix.size() - 4);
    if (mid.empty() ||
        mid.find_first_not_of("0123456789") != std::string::npos)
      continue;
    out.emplace_back(strtoull(mid.c_str(), nullptr, 10), dir + "/" + name);
  }
  closedir(d);
  std::sort(out.begin(), out.end());
  return out;
}

bool file_has_magic(const std::string &path) {
  FILE *f = fopen(path.c_str(), "rb");
  if (!f) return false;
  char head[4];
  bool ok = fread(head, 1, 4, f) == 4 && memcmp(head, MAGIC, 4) == 0;
  fclose(f);
  return ok;
}

// Does `buf[from..]` hold a CRC-valid v2 record with a plausible forward
// sequence number at ANY offset?  A real torn write leaves nothing after
// the cut, so a hit means mid-log corruption, not a tear (mirror of the
// Python reader's _resync_finds_record).
bool resync_finds_record(const std::vector<char> &raw, size_t from,
                         uint32_t expect_seq) {
  const uint8_t *buf = reinterpret_cast<const uint8_t *>(raw.data());
  size_t n = raw.size();
  uint64_t horizon = uint64_t(expect_seq) + 1000000;
  for (size_t off = from; off + REC_V2_HDR <= n; ++off) {
    uint8_t op = buf[off + 8];
    if (op != OP_PUT && op != OP_DEL) continue;
    uint32_t crc, seq, klen, vlen;
    memcpy(&crc, buf + off, 4);
    memcpy(&seq, buf + off + 4, 4);
    memcpy(&klen, buf + off + 9, 4);
    memcpy(&vlen, buf + off + 13, 4);
    if (seq < expect_seq || uint64_t(seq) > horizon) continue;
    size_t end = off + REC_V2_HDR + size_t(klen) + vlen;
    if (end > n) continue;
    if (crc32(buf + off + 4, end - off - 4) == crc) return true;
  }
  return false;
}

struct Store {
  std::string path;
  std::map<std::string, std::string> data;
  FILE* file = nullptr;
  uint64_t dead_bytes = 0;
  uint64_t live_bytes = 0;
  bool v2 = false;              // segmented-log mode
  uint64_t active_seq = 0;      // v2: active segment sequence number
  uint32_t rec_seq = 0;         // v2: next record seq in the active segment
  uint64_t active_bytes = 0;    // v2: bytes in the active segment
  std::vector<std::pair<uint64_t, std::string>> segments;  // v2: sealed

  ~Store() {
    if (file) fclose(file);
  }

  size_t rec_overhead() const { return v2 ? REC_V2_HDR : REC_HDR; }

  void note_replace(const std::string& key) {
    auto it = data.find(key);
    if (it != data.end()) {
      uint64_t dead = rec_overhead() + key.size() + it->second.size();
      dead_bytes += dead;
      live_bytes -= dead;
    }
  }

  void apply(uint8_t op, std::string key, const char *val, size_t vlen,
             size_t rec_size) {
    note_replace(key);
    if (op == OP_PUT) {
      data[std::move(key)] = std::string(val, vlen);
      live_bytes += rec_size;
    } else {
      data.erase(key);
      dead_bytes += rec_size;
    }
  }

  static void put_rec_v1(std::string& out, uint8_t op, const char* k,
                         uint32_t klen, const char* v, uint32_t vlen) {
    uint8_t hdr[REC_HDR];
    hdr[0] = op;
    put_u32(hdr + 1, klen);
    put_u32(hdr + 5, vlen);
    out.append(reinterpret_cast<char *>(hdr), REC_HDR);
    out.append(k, klen);
    if (vlen) out.append(v, vlen);
  }

  void put_rec_v2(std::string& out, uint8_t op, const char* k, uint32_t klen,
                  const char* v, uint32_t vlen) {
    uint8_t hdr[REC_V2_HDR];
    put_u32(hdr + 4, rec_seq++);
    hdr[8] = op;
    put_u32(hdr + 9, klen);
    put_u32(hdr + 13, vlen);
    size_t body_at = out.size() + 4;
    out.append(reinterpret_cast<char *>(hdr), REC_V2_HDR);
    out.append(k, klen);
    if (vlen) out.append(v, vlen);
    uint32_t crc = crc32(
        reinterpret_cast<const uint8_t *>(out.data()) + body_at,
        out.size() - body_at);
    memcpy(&out[body_at - 4], &crc, 4);
  }

  // -- replay ---------------------------------------------------------------

  enum ReplayResult { RP_OK, RP_FAIL };

  static bool read_all(const std::string &p, std::vector<char> &raw) {
    FILE *f = fopen(p.c_str(), "rb");
    if (!f) return false;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    raw.resize(size_t(sz));
    bool ok = !sz || fread(raw.data(), 1, raw.size(), f) == raw.size();
    fclose(f);
    return ok;
  }

  // v1 records from `raw[pos..]`; anomalies stop the scan.  In the last
  // file the unparseable tail is truncated away (pre-v2 behavior); in a
  // sealed file it is a hard failure.
  ReplayResult replay_v1(const std::string &p, std::vector<char> &raw,
                         size_t pos, bool is_last) {
    size_t good = pos;
    while (pos + REC_HDR <= raw.size()) {
      uint8_t op = uint8_t(raw[pos]);
      uint32_t klen, vlen;
      memcpy(&klen, raw.data() + pos + 1, 4);
      memcpy(&vlen, raw.data() + pos + 5, 4);
      size_t end = pos + REC_HDR + size_t(klen) + vlen;
      if (end > raw.size() || (op != OP_PUT && op != OP_DEL)) break;
      apply(op, std::string(raw.data() + pos + REC_HDR, klen),
            raw.data() + pos + REC_HDR + klen, vlen, end - pos);
      pos = end;
      good = pos;
    }
    if (good < raw.size()) {
      if (!is_last) return RP_FAIL;
      if (truncate(p.c_str(), off_t(good)) != 0) return RP_FAIL;
    }
    return RP_OK;
  }

  // v2 records after the file header; CRC + sequence validated.  Torn
  // tail of the last file truncated; anything else refuses (salvage is
  // the Python reader's job).
  ReplayResult replay_v2(const std::string &p, std::vector<char> &raw,
                         bool is_last) {
    if (raw.size() < FILE_HDR) {
      // header itself torn: an empty just-created file
      if (!is_last) return RP_FAIL;
      return truncate(p.c_str(), 0) == 0 ? RP_OK : RP_FAIL;
    }
    uint16_t version;
    memcpy(&version, raw.data() + 4, 2);
    if (version > FMT_VERSION) return RP_FAIL;  // newer than this reader
    size_t pos = FILE_HDR, good = pos;
    uint32_t expect_seq = 0;
    const uint8_t *buf = reinterpret_cast<const uint8_t *>(raw.data());
    while (pos + REC_V2_HDR <= raw.size()) {
      uint32_t crc, seq, klen, vlen;
      memcpy(&crc, buf + pos, 4);
      memcpy(&seq, buf + pos + 4, 4);
      uint8_t op = buf[pos + 8];
      memcpy(&klen, buf + pos + 9, 4);
      memcpy(&vlen, buf + pos + 13, 4);
      size_t end = pos + REC_V2_HDR + size_t(klen) + vlen;
      if (end > raw.size()) break;  // cut mid-record
      if (seq != expect_seq || (op != OP_PUT && op != OP_DEL) ||
          crc32(buf + pos + 4, end - pos - 4) != crc) {
        // a COMPLETE record failing validation is corruption, torn or
        // not — refuse (the Python reader quarantines)
        return RP_FAIL;
      }
      apply(op, std::string(raw.data() + pos + REC_V2_HDR, klen),
            raw.data() + pos + REC_V2_HDR + klen, vlen, end - pos);
      pos = end;
      good = pos;
      ++expect_seq;
    }
    if (good < raw.size()) {
      if (!is_last) return RP_FAIL;
      // last file: a true tear has no valid successor records after the
      // cut — if one exists this is mid-log damage and must stay loud
      if (resync_finds_record(raw, good, expect_seq)) return RP_FAIL;
      if (truncate(p.c_str(), off_t(good)) != 0) return RP_FAIL;
    }
    if (is_last) rec_seq = expect_seq;
    return RP_OK;
  }

  ReplayResult replay_file(const std::string &p, bool is_last) {
    std::vector<char> raw;
    if (!read_all(p, raw)) return RP_FAIL;
    if (raw.size() >= 4 && memcmp(raw.data(), MAGIC, 4) == 0)
      return replay_v2(p, raw, is_last);
    return replay_v1(p, raw, 0, is_last);
  }

  // -- open -----------------------------------------------------------------

  bool open_v1() {
    std::vector<char> raw;
    FILE *probe = fopen(path.c_str(), "rb");
    if (probe) {
      fclose(probe);
      if (replay_file(path, /*is_last=*/true) != RP_OK) return false;
    }
    file = fopen(path.c_str(), "ab");
    return file != nullptr;
  }

  bool open_v2() {
    // stale compaction temp: contents are a subset of base+segments
    std::string tmp = path + ".compact";
    if (remove(tmp.c_str()) == 0) fsync_dir(dirname_of(path));
    segments = list_segments(path);
    FILE *probe = fopen(path.c_str(), "rb");
    if (probe) {
      fclose(probe);
      if (replay_file(path, /*is_last=*/segments.empty()) != RP_OK)
        return false;
    }
    for (size_t i = 0; i < segments.size(); ++i) {
      if (replay_file(segments[i].second,
                      /*is_last=*/i + 1 == segments.size()) != RP_OK)
        return false;
    }
    // Fresh segment for OUR appends (never resume another writer's
    // segment: the LogKV resume rules — headerless-husk handling,
    // mid-segment seq continuation — stay that engine's; an extra
    // segment replays identically everywhere).
    uint64_t next = segments.empty() ? 1 : segments.back().first + 1;
    return new_segment(next);
  }

  bool new_segment(uint64_t seq) {
    if (file) {
      fflush(file);
      fclose(file);
      file = nullptr;
      segments.emplace_back(active_seq, seg_path(path, active_seq));
    }
    std::string p = seg_path(path, seq);
    file = fopen(p.c_str(), "ab");
    if (!file) return false;
    uint8_t hdr[FILE_HDR];
    memcpy(hdr, MAGIC, 4);
    uint16_t v = FMT_VERSION, kind = KIND_LOG;
    memcpy(hdr + 4, &v, 2);
    memcpy(hdr + 6, &kind, 2);
    put_u64(hdr + 8, seq);
    if (fwrite(hdr, 1, FILE_HDR, file) != FILE_HDR) return false;
    if (fflush(file) != 0) return false;
    fsync(fileno(file));
    fsync_dir(dirname_of(path));
    active_seq = seq;
    active_bytes = FILE_HDR;
    rec_seq = 0;
    return true;
  }

  bool open() {
    v2 = !list_segments(path).empty() || file_has_magic(path);
    return v2 ? open_v2() : open_v1();
  }

  // -- write path -----------------------------------------------------------

  // `ops` parsed from the ABI blob: (op, key, value).
  bool commit(const std::vector<std::tuple<uint8_t, std::string, std::string>>
                  &ops,
              bool do_fsync) {
    if (v2 && active_bytes >= SEG_LIMIT) {
      if (!new_segment(active_seq + 1)) return false;
    }
    std::string blob;
    for (const auto &[op, k, val] : ops) {
      if (v2)
        put_rec_v2(blob, op, k.data(), uint32_t(k.size()), val.data(),
                   uint32_t(val.size()));
      else
        put_rec_v1(blob, op, k.data(), uint32_t(k.size()), val.data(),
                   uint32_t(val.size()));
    }
    if (fwrite(blob.data(), 1, blob.size(), file) != blob.size()) return false;
    if (fflush(file) != 0) return false;
    if (do_fsync && fsync(fileno(file)) != 0) return false;
    active_bytes += blob.size();
    for (const auto &[op, k, val] : ops)
      apply(op, k, val.data(), val.size(),
            rec_overhead() + k.size() + val.size());
    if (dead_bytes >= (1u << 20) && dead_bytes >= 3 * live_bytes)
      compact();  // opportunistic: the write above is already durable, and
                  // a failed compaction reopens the log and keeps going
    return file != nullptr;
  }

  // -- compaction -----------------------------------------------------------

  bool compact() { return v2 ? compact_v2() : compact_v1(); }

  bool compact_v1() {
    // The old log handle is only closed after the new file is fully
    // written; on ANY failure the handle is re-opened so the store stays
    // writable (a failed compaction must degrade, not poison the Store).
    std::string tmp = path + ".compact";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    std::string blob;
    for (auto& [k, v] : data) {
      blob.clear();
      put_rec_v1(blob, OP_PUT, k.data(), uint32_t(k.size()),
                 v.data(), uint32_t(v.size()));
      if (fwrite(blob.data(), 1, blob.size(), f) != blob.size()) {
        fclose(f);
        remove(tmp.c_str());
        return false;
      }
    }
    if (fflush(f) != 0 || fsync(fileno(f)) != 0) {
      fclose(f);
      remove(tmp.c_str());
      return false;
    }
    fclose(f);
    fclose(file);
    file = nullptr;
    bool ok = rename(tmp.c_str(), path.c_str()) == 0;
    file = fopen(path.c_str(), "ab");  // reopen whichever file now exists
    if (!ok || !file) return false;
    dead_bytes = 0;
    live_bytes = 0;
    for (auto& [k, v] : data) live_bytes += REC_HDR + k.size() + v.size();
    return true;
  }

  // v2: write a full snapshot over the base path, then drop every sealed
  // segment and the pre-compaction active one.  Crash-safe in the LogKV
  // sense: before the rename the old base+segments are intact (the temp
  // is swept on open); after it the snapshot holds every record and any
  // leftover segment merely re-applies idempotent writes.
  bool compact_v2() {
    std::string tmp = path + ".compact";
    FILE *f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    uint8_t hdr[FILE_HDR];
    memcpy(hdr, MAGIC, 4);
    uint16_t ver = FMT_VERSION, kind = KIND_SNAPSHOT;
    memcpy(hdr + 4, &ver, 2);
    memcpy(hdr + 6, &kind, 2);
    put_u64(hdr + 8, 0);
    bool ok = fwrite(hdr, 1, FILE_HDR, f) == FILE_HDR;
    std::string blob;
    uint32_t snap_seq = 0;
    for (auto &[k, v] : data) {
      if (!ok) break;
      blob.clear();
      uint8_t rh[REC_V2_HDR];
      put_u32(rh + 4, snap_seq++);
      rh[8] = OP_PUT;
      put_u32(rh + 9, uint32_t(k.size()));
      put_u32(rh + 13, uint32_t(v.size()));
      blob.append(reinterpret_cast<char *>(rh), REC_V2_HDR);
      blob.append(k);
      blob.append(v);
      uint32_t crc = crc32(
          reinterpret_cast<const uint8_t *>(blob.data()) + 4,
          blob.size() - 4);
      memcpy(&blob[0], &crc, 4);
      ok = fwrite(blob.data(), 1, blob.size(), f) == blob.size();
    }
    if (!ok || fflush(f) != 0 || fsync(fileno(f)) != 0) {
      fclose(f);
      remove(tmp.c_str());
      return false;
    }
    fclose(f);
    fsync_dir(dirname_of(path));
    // seal the active segment so the whole pre-snapshot tail is doomed
    std::vector<std::pair<uint64_t, std::string>> doomed = segments;
    doomed.emplace_back(active_seq, seg_path(path, active_seq));
    fclose(file);
    file = nullptr;
    if (rename(tmp.c_str(), path.c_str()) != 0) {
      // degrade, stay writable: the old base+segments remain the store —
      // keep EVERY sealed segment tracked (including the just-sealed
      // active one) so a later successful compaction deletes them all;
      // forgetting them here would leave stale files that replay after
      // that newer snapshot and resurrect deleted keys
      remove(tmp.c_str());
      segments = doomed;
      return new_segment(doomed.back().first + 1);
    }
    segments.clear();
    fsync_dir(dirname_of(path));
    for (auto &[seq, p] : doomed) {
      (void)seq;
      remove(p.c_str());
    }
    fsync_dir(dirname_of(path));
    if (!new_segment(doomed.back().first + 1)) return false;
    dead_bytes = 0;
    live_bytes = 0;
    for (auto &[k, v] : data) live_bytes += REC_V2_HDR + k.size() + v.size();
    return true;
  }
};

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  auto* s = new Store();
  s->path = path;
  if (!s->open()) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) { delete static_cast<Store*>(h); }

// 1 = v2 segmented directory, 0 = legacy v1 single file.
int kv_format(void* h) { return static_cast<Store*>(h)->v2 ? 1 : 0; }

// 1 = found (out/outlen set, free with kv_buf_free), 0 = missing.
int kv_get(void* h, const char* key, uint32_t klen, char** out,
           uint64_t* outlen) {
  auto* s = static_cast<Store*>(h);
  auto it = s->data.find(std::string(key, klen));
  if (it == s->data.end()) return 0;
  *outlen = it->second.size();
  *out = static_cast<char*>(malloc(it->second.size() ? it->second.size() : 1));
  memcpy(*out, it->second.data(), it->second.size());
  return 1;
}

// blob = concatenated records in the v1 ABI format (op u8, klen u32le,
// vlen u32le, key, value) regardless of the on-disk mode.  0 = ok.
int kv_write_batch(void* h, const char* blob, uint64_t len, int do_fsync) {
  auto* s = static_cast<Store*>(h);
  size_t pos = 0;
  std::vector<std::tuple<uint8_t, std::string, std::string>> ops;
  while (pos + REC_HDR <= len) {
    uint8_t op = static_cast<uint8_t>(blob[pos]);
    uint32_t klen, vlen;
    memcpy(&klen, blob + pos + 1, 4);
    memcpy(&vlen, blob + pos + 5, 4);
    size_t end = pos + REC_HDR + static_cast<size_t>(klen) + vlen;
    if (end > len || (op != OP_PUT && op != OP_DEL)) return -1;
    ops.emplace_back(op, std::string(blob + pos + REC_HDR, klen),
                     std::string(blob + pos + REC_HDR + klen, vlen));
    pos = end;
  }
  if (pos != len) return -1;
  return s->commit(ops, do_fsync != 0) ? 0 : -2;
}

// Serialize every (key, value) with key starting with prefix, in key order,
// as klen(u32le) vlen(u32le) key value records.  Free with kv_buf_free.
int kv_scan_prefix(void* h, const char* prefix, uint32_t plen, char** out,
                   uint64_t* outlen) {
  auto* s = static_cast<Store*>(h);
  std::string pfx(prefix, plen);
  std::string buf;
  for (auto it = s->data.lower_bound(pfx); it != s->data.end(); ++it) {
    if (it->first.compare(0, pfx.size(), pfx) != 0) break;
    uint32_t klen = static_cast<uint32_t>(it->first.size());
    uint32_t vlen = static_cast<uint32_t>(it->second.size());
    char hdr[8];
    memcpy(hdr, &klen, 4);
    memcpy(hdr + 4, &vlen, 4);
    buf.append(hdr, 8);
    buf.append(it->first);
    buf.append(it->second);
  }
  *outlen = buf.size();
  *out = static_cast<char*>(malloc(buf.size() ? buf.size() : 1));
  memcpy(*out, buf.data(), buf.size());
  return 0;
}

int kv_compact(void* h) {
  return static_cast<Store*>(h)->compact() ? 0 : -1;
}

uint64_t kv_count(void* h) { return static_cast<Store*>(h)->data.size(); }

void kv_buf_free(char* p) { free(p); }

}  // extern "C"
