// secp256k1 ECDSA batch verification — C++ CPU engine.
//
// The reference consumes libsecp256k1 (C) through haskoin-core
// (reference /root/reference/stack.yaml:5,9; SURVEY.md C9).  This is the
// framework's native CPU equivalent: the single-core baseline the TPU kernel
// is benchmarked against, and the small-batch fallback path of
// tpunode/verify/engine.py.  Written from scratch: 4x64-bit limb field
// arithmetic with __int128 products, Jacobian points (a = 0), and interleaved
// 4-bit fixed-window double-and-add (Shamir's trick) for u1*G + u2*Q.
//
// Exposed C ABI (ctypes): secp_verify_batch().

#include <cstdint>
#include <cstring>

namespace {

typedef unsigned __int128 u128;

// ---------- 256-bit field element, little-endian u64 limbs ----------

struct Fe {
  uint64_t v[4];
};

// p = 2^256 - 0x1000003D1
constexpr uint64_t P0 = 0xFFFFFFFEFFFFFC2FULL;
constexpr uint64_t P1 = 0xFFFFFFFFFFFFFFFFULL;
constexpr uint64_t P2 = 0xFFFFFFFFFFFFFFFFULL;
constexpr uint64_t P3 = 0xFFFFFFFFFFFFFFFFULL;
constexpr uint64_t PC = 0x1000003D1ULL;  // 2^256 mod p

// n = group order
constexpr uint64_t N0 = 0xBFD25E8CD0364141ULL;
constexpr uint64_t N1 = 0xBAAEDCE6AF48A03BULL;
constexpr uint64_t N2 = 0xFFFFFFFFFFFFFFFEULL;
constexpr uint64_t N3 = 0xFFFFFFFFFFFFFFFFULL;

inline bool ge(const Fe &a, const uint64_t m[4]) {
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] > m[i]) return true;
    if (a.v[i] < m[i]) return false;
  }
  return true;  // equal
}

inline void sub_mod_raw(Fe &a, const uint64_t m[4]) {
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - m[i] - (uint64_t)borrow;
    a.v[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

inline bool is_zero(const Fe &a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline bool fe_eq(const Fe &a, const Fe &b) {
  return a.v[0] == b.v[0] && a.v[1] == b.v[1] && a.v[2] == b.v[2] &&
         a.v[3] == b.v[3];
}

struct Mod {
  uint64_t m[4];   // modulus
  uint64_t fold;   // 2^256 mod m (single limb for both p and n folds)
  uint64_t fold1;  // second limb of 2^256 mod m (n needs 3 limbs; see below)
  uint64_t fold2;
};

// 2^256 mod n = 2^256 - n  (since 2^255 < n < 2^256)
// = 0x...01457365 4... compute: (~n + 1) over 256 bits.
constexpr uint64_t NF0 = 0x402DA1732FC9BEBFULL;  // -N0 mod 2^64 with borrows
constexpr uint64_t NF1 = 0x4551231950B75FC4ULL;
constexpr uint64_t NF2 = 0x0000000000000001ULL;
constexpr uint64_t NF3 = 0x0000000000000000ULL;

inline void add_limb_at(uint64_t t[9], int idx, uint64_t val) {
  u128 cur = (u128)t[idx] + val;
  t[idx] = (uint64_t)cur;
  uint64_t carry = (uint64_t)(cur >> 64);
  for (int i = idx + 1; carry && i < 9; ++i) {
    u128 c2 = (u128)t[i] + carry;
    t[i] = (uint64_t)c2;
    carry = (uint64_t)(c2 >> 64);
  }
}

// Generic 512-bit -> 256-bit reduction given fold = 2^256 mod m (up to 3 limbs).
inline Fe reduce512(const uint64_t t[8], const uint64_t fold[4],
                    const uint64_t m[4]) {
  // r = lo + hi * fold ; hi*fold <= (2^256)(2^130ish) so iterate twice.
  uint64_t acc[9];
  std::memcpy(acc, t, 8 * sizeof(uint64_t));
  acc[8] = 0;
  for (int round = 0; round < 2; ++round) {
    uint64_t hi[5];
    std::memcpy(hi, acc + 4, 4 * sizeof(uint64_t));
    hi[4] = acc[8];
    uint64_t lo[9];
    std::memcpy(lo, acc, 4 * sizeof(uint64_t));
    std::memset(lo + 4, 0, 5 * sizeof(uint64_t));
    // lo += hi * fold
    for (int i = 0; i < 5; ++i) {
      if (hi[i] == 0) continue;
      for (int j = 0; j < 4; ++j) {
        if (fold[j] == 0) continue;
        u128 prod = (u128)hi[i] * fold[j];
        add_limb_at(lo, i + j, (uint64_t)prod);
        if ((uint64_t)(prod >> 64)) add_limb_at(lo, i + j + 1, (uint64_t)(prod >> 64));
      }
    }
    std::memcpy(acc, lo, 9 * sizeof(uint64_t));
    acc[8] = lo[8];
  }
  Fe r{{acc[0], acc[1], acc[2], acc[3]}};
  // after two folds the high limbs are tiny; fold remaining once more
  uint64_t hi4 = acc[4];
  if (hi4 | acc[5] | acc[6] | acc[7] | acc[8]) {
    uint64_t lo[9] = {r.v[0], r.v[1], r.v[2], r.v[3], 0, 0, 0, 0, 0};
    uint64_t hi[5] = {acc[4], acc[5], acc[6], acc[7], acc[8]};
    for (int i = 0; i < 5; ++i) {
      if (hi[i] == 0) continue;
      for (int j = 0; j < 4; ++j) {
        if (fold[j] == 0) continue;
        u128 prod = (u128)hi[i] * fold[j];
        add_limb_at(lo, i + j, (uint64_t)prod);
        if ((uint64_t)(prod >> 64)) add_limb_at(lo, i + j + 1, (uint64_t)(prod >> 64));
      }
    }
    r = Fe{{lo[0], lo[1], lo[2], lo[3]}};
  }
  while (ge(r, m)) sub_mod_raw(r, m);
  return r;
}

struct Field {
  uint64_t m[4];
  uint64_t fold[4];

  Fe add(const Fe &a, const Fe &b) const {
    Fe r;
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
      u128 s = (u128)a.v[i] + b.v[i] + (uint64_t)carry;
      r.v[i] = (uint64_t)s;
      carry = s >> 64;
    }
    if (carry) {
      // r += fold (2^256 mod m)
      u128 c2 = 0;
      for (int i = 0; i < 4; ++i) {
        u128 s = (u128)r.v[i] + fold[i] + (uint64_t)c2;
        r.v[i] = (uint64_t)s;
        c2 = s >> 64;
      }
    }
    while (ge(r, m)) sub_mod_raw(r, m);
    return r;
  }

  Fe sub(const Fe &a, const Fe &b) const {
    Fe r = a;
    if (!ge(r, b.v)) {
      // r += m first
      u128 carry = 0;
      for (int i = 0; i < 4; ++i) {
        u128 s = (u128)r.v[i] + m[i] + (uint64_t)carry;
        r.v[i] = (uint64_t)s;
        carry = s >> 64;
      }
      // a < b <= m so a+m-b < m: carry out of 2^256 may happen; ignore since
      // result computed with borrow below stays correct modulo 2^256 when
      // carry==1 cancels the borrow.
    }
    sub_mod_raw(r, b.v);
    return r;
  }

  Fe mul(const Fe &a, const Fe &b) const {
    uint64_t t[8] = {0};
    for (int i = 0; i < 4; ++i) {
      uint64_t carry = 0;
      for (int j = 0; j < 4; ++j) {
        u128 cur = (u128)a.v[i] * b.v[j] + t[i + j] + carry;
        t[i + j] = (uint64_t)cur;
        carry = (uint64_t)(cur >> 64);
      }
      t[i + 4] = carry;
    }
    if (fold[1] == 0) {
      // Single-limb fold constant (the field prime p): fast two-pass fold.
      // r = lo + hi*PC where PC = 2^256 mod p fits one limb.
      uint64_t c = fold[0];
      uint64_t lo[5] = {t[0], t[1], t[2], t[3], 0};
      uint64_t carry = 0;
      for (int i = 0; i < 4; ++i) {
        u128 cur = (u128)t[4 + i] * c + lo[i] + carry;
        lo[i] = (uint64_t)cur;
        carry = (uint64_t)(cur >> 64);
      }
      lo[4] = carry;
      // second fold: lo[4] * c
      u128 cur = (u128)lo[4] * c + lo[0];
      Fe r{{(uint64_t)cur, lo[1], lo[2], lo[3]}};
      uint64_t c2 = (uint64_t)(cur >> 64);
      for (int i = 1; c2 && i < 4; ++i) {
        u128 s2 = (u128)r.v[i] + c2;
        r.v[i] = (uint64_t)s2;
        c2 = (uint64_t)(s2 >> 64);
      }
      // c2 can only be nonzero if r wrapped; fold once more
      if (c2) {
        u128 s3 = (u128)r.v[0] + c;
        r.v[0] = (uint64_t)s3;
        uint64_t c3 = (uint64_t)(s3 >> 64);
        for (int i = 1; c3 && i < 4; ++i) {
          u128 s4 = (u128)r.v[i] + c3;
          r.v[i] = (uint64_t)s4;
          c3 = (uint64_t)(s4 >> 64);
        }
      }
      while (ge(r, m)) sub_mod_raw(r, m);
      return r;
    }
    return reduce512(t, fold, m);
  }

  Fe sqr(const Fe &a) const { return mul(a, a); }

  Fe pow(const Fe &a, const uint64_t e[4]) const {
    Fe result{{1, 0, 0, 0}};
    Fe base = a;
    for (int limb = 0; limb < 4; ++limb) {
      uint64_t bits = e[limb];
      for (int i = 0; i < 64; ++i) {
        if (bits & 1) result = mul(result, base);
        base = sqr(base);
        bits >>= 1;
      }
    }
    return result;
  }

  Fe inv(const Fe &a) const {
    // Fermat: a^(m-2); both p and n are prime.
    uint64_t e[4] = {m[0] - 2, m[1], m[2], m[3]};  // m odd, no borrow
    return pow(a, e);
  }
};

const Field FP = {{P0, P1, P2, P3}, {PC, 0, 0, 0}};
const Field FN = {{N0, N1, N2, N3}, {NF0, NF1, NF2, NF3}};

// ---------- Jacobian points, a = 0, b = 7 ----------

struct Pt {
  Fe x, y, z;  // z == 0 => infinity
};

inline bool pt_inf(const Pt &p) { return is_zero(p.z); }

Pt pt_double(const Pt &p) {
  if (pt_inf(p) || is_zero(p.y)) return Pt{{{0}}, {{1, 0, 0, 0}}, {{0}}};
  // dbl-2009-l: A=X^2, B=Y^2, C=B^2, D=2((X+B)^2-A-C), E=3A, F=E^2
  Fe A = FP.sqr(p.x);
  Fe B = FP.sqr(p.y);
  Fe C = FP.sqr(B);
  Fe t = FP.sqr(FP.add(p.x, B));
  Fe D = FP.sub(FP.sub(t, A), C);
  D = FP.add(D, D);
  Fe E = FP.add(FP.add(A, A), A);
  Fe F = FP.sqr(E);
  Pt r;
  r.x = FP.sub(F, FP.add(D, D));
  Fe C8 = FP.add(C, C);
  C8 = FP.add(C8, C8);
  C8 = FP.add(C8, C8);
  r.y = FP.sub(FP.mul(E, FP.sub(D, r.x)), C8);
  r.z = FP.mul(FP.add(p.y, p.y), p.z);
  return r;
}

Pt pt_add(const Pt &p, const Pt &q) {
  if (pt_inf(p)) return q;
  if (pt_inf(q)) return p;
  // add-2007-bl
  Fe Z1Z1 = FP.sqr(p.z);
  Fe Z2Z2 = FP.sqr(q.z);
  Fe U1 = FP.mul(p.x, Z2Z2);
  Fe U2 = FP.mul(q.x, Z1Z1);
  Fe S1 = FP.mul(FP.mul(p.y, q.z), Z2Z2);
  Fe S2 = FP.mul(FP.mul(q.y, p.z), Z1Z1);
  if (fe_eq(U1, U2)) {
    if (fe_eq(S1, S2)) return pt_double(p);
    return Pt{{{0}}, {{1, 0, 0, 0}}, {{0}}};  // P + (-P) = O
  }
  Fe H = FP.sub(U2, U1);
  Fe I = FP.sqr(FP.add(H, H));
  Fe J = FP.mul(H, I);
  Fe rr = FP.sub(S2, S1);
  rr = FP.add(rr, rr);
  Fe V = FP.mul(U1, I);
  Pt out;
  out.x = FP.sub(FP.sub(FP.sqr(rr), J), FP.add(V, V));
  Fe S1J = FP.mul(S1, J);
  out.y = FP.sub(FP.mul(rr, FP.sub(V, out.x)), FP.add(S1J, S1J));
  Fe z1z2 = FP.mul(p.z, q.z);
  out.z = FP.mul(FP.add(z1z2, z1z2), H);  // add-2007-bl: Z3 = 2*Z1*Z2*H
  return out;
}

Fe fe_from_be(const uint8_t *b) {
  Fe r;
  for (int i = 0; i < 4; ++i) {
    uint64_t limb = 0;
    for (int j = 0; j < 8; ++j) limb = (limb << 8) | b[(3 - i) * 8 + j];
    r.v[i] = limb;
  }
  return r;
}

// Generator
const Fe GX = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
const Fe GY = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

struct Tables {
  Pt g[16];
  Tables() {
    g[0] = Pt{{{0}}, {{1, 0, 0, 0}}, {{0}}};
    g[1] = Pt{GX, GY, {{1, 0, 0, 0}}};
    for (int i = 2; i < 16; ++i) g[i] = pt_add(g[i - 1], g[1]);
  }
};
const Tables TAB;

// w = s^-1 mod n, precomputed by the caller (batch inversion).
bool verify_one(const uint8_t *px, const uint8_t *py, const uint8_t *z32,
                const uint8_t *r32, const Fe &w) {
  Fe qx = fe_from_be(px), qy = fe_from_be(py);
  Fe z = fe_from_be(z32);
  while (ge(z, FN.m)) sub_mod_raw(z, FN.m);  // digest reduced mod n
  Fe r = fe_from_be(r32);
  if (is_zero(r) || ge(r, FN.m)) return false;
  // curve membership: qy^2 == qx^3 + 7, coords < p
  if (ge(qx, FP.m) || ge(qy, FP.m)) return false;
  Fe lhs = FP.sqr(qy);
  Fe rhs = FP.add(FP.mul(FP.sqr(qx), qx), Fe{{7, 0, 0, 0}});
  if (!fe_eq(lhs, rhs)) return false;

  Fe u1 = FN.mul(z, w);
  Fe u2 = FN.mul(r, w);

  // per-key table
  Pt tq[16];
  tq[0] = Pt{{{0}}, {{1, 0, 0, 0}}, {{0}}};
  tq[1] = Pt{qx, qy, {{1, 0, 0, 0}}};
  for (int i = 2; i < 16; ++i) tq[i] = pt_add(tq[i - 1], tq[1]);

  // interleaved 4-bit windows, MSB first
  Pt acc = Pt{{{0}}, {{1, 0, 0, 0}}, {{0}}};
  for (int w4 = 63; w4 >= 0; --w4) {
    if (!pt_inf(acc)) {
      acc = pt_double(acc);
      acc = pt_double(acc);
      acc = pt_double(acc);
      acc = pt_double(acc);
    }
    int limb = w4 / 16, shift = (w4 % 16) * 4;
    int d1 = (int)((u1.v[limb] >> shift) & 0xF);
    int d2 = (int)((u2.v[limb] >> shift) & 0xF);
    if (d1) acc = pt_add(acc, TAB.g[d1]);
    if (d2) acc = pt_add(acc, tq[d2]);
  }
  if (pt_inf(acc)) return false;
  // accept iff acc.X == (r + k*n) * acc.Z^2 mod p for k in {0,1} with r+kn < p
  Fe zz = FP.sqr(acc.z);
  Fe cand = r;  // r < n < p: valid candidate
  if (fe_eq(FP.mul(cand, zz), acc.x)) return true;
  // second candidate r + n if it fits below p
  Fe rn = r;
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 s2 = (u128)rn.v[i] + FN.m[i] + (uint64_t)carry;
    rn.v[i] = (uint64_t)s2;
    carry = s2 >> 64;
  }
  if (!carry && !ge(rn, FP.m)) {
    if (fe_eq(FP.mul(rn, zz), acc.x)) return true;
  }
  return false;
}

// Euler's criterion: a^((p-1)/2) == 1 (mod p) — the jacobi(y) = 1
// acceptance test of BCH Schnorr.  Square-and-multiply over the constant
// exponent, MSB first.
bool fe_euler_is_one(const Fe &a) {
  // (p-1)/2, big-endian limb order for MSB-first iteration
  static const uint64_t E[4] = {0x7FFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL,
                                0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFF7FFFFE17ULL};
  Fe acc{{1, 0, 0, 0}};
  bool started = false;
  for (int w = 0; w < 4; ++w) {
    for (int b = 63; b >= 0; --b) {
      if (started) acc = FP.sqr(acc);
      if ((E[w] >> b) & 1) {
        if (started)
          acc = FP.mul(acc, a);
        else {
          acc = a;
          started = true;
        }
      }
    }
  }
  Fe one{{1, 0, 0, 0}};
  return fe_eq(acc, one);
}

// Shared core of both Schnorr-family verifiers (BCH 2019 and BIP340):
// identical range rules (r < p, s < n, zero allowed), curve membership,
// u1 = s / u2 = n - e, and the window MSM — only the final acceptance
// test differs (jacobi(y) = 1 vs y even), exactly as the TPU kernel
// splits it with per-lane flags.  Returns false on any pre-acceptance
// failure; on success fills r_out and the Jacobian accumulator.
bool schnorr_msm(const uint8_t *px, const uint8_t *py, const uint8_t *e32,
                 const uint8_t *r32, const uint8_t *s32, Fe &r_out,
                 Pt &acc_out) {
  Fe qx = fe_from_be(px), qy = fe_from_be(py);
  r_out = fe_from_be(r32);
  if (ge(r_out, FP.m)) return false;  // r is an Fp x-coordinate
  Fe s = fe_from_be(s32);
  if (ge(s, FN.m)) return false;  // s a scalar (zero allowed by spec)
  if (ge(qx, FP.m) || ge(qy, FP.m)) return false;
  Fe lhs = FP.sqr(qy);
  Fe rhs = FP.add(FP.mul(FP.sqr(qx), qx), Fe{{7, 0, 0, 0}});
  if (!fe_eq(lhs, rhs)) return false;

  Fe e = fe_from_be(e32);
  while (ge(e, FN.m)) sub_mod_raw(e, FN.m);
  // u2 = n - e (mod n)
  Fe u2{{0, 0, 0, 0}};
  if (!is_zero(e)) {
    u2 = Fe{{FN.m[0], FN.m[1], FN.m[2], FN.m[3]}};
    sub_mod_raw(u2, e.v);
  }
  const Fe &u1 = s;

  Pt tq[16];
  tq[0] = Pt{{{0}}, {{1, 0, 0, 0}}, {{0}}};
  tq[1] = Pt{qx, qy, {{1, 0, 0, 0}}};
  for (int i = 2; i < 16; ++i) tq[i] = pt_add(tq[i - 1], tq[1]);

  Pt acc = Pt{{{0}}, {{1, 0, 0, 0}}, {{0}}};
  for (int w4 = 63; w4 >= 0; --w4) {
    if (!pt_inf(acc)) {
      acc = pt_double(acc);
      acc = pt_double(acc);
      acc = pt_double(acc);
      acc = pt_double(acc);
    }
    int limb = w4 / 16, shift = (w4 % 16) * 4;
    int d1 = (int)((u1.v[limb] >> shift) & 0xF);
    int d2 = (int)((u2.v[limb] >> shift) & 0xF);
    if (d1) acc = pt_add(acc, TAB.g[d1]);
    if (d2) acc = pt_add(acc, tq[d2]);
  }
  if (pt_inf(acc)) return false;
  // x(R) == r over Fp (Jacobian: X == r * Z^2)
  Fe zz = FP.sqr(acc.z);
  if (!fe_eq(FP.mul(r_out, zz), acc.x)) return false;
  acc_out = acc;
  return true;
}

// BCH Schnorr (2019-05 upgrade spec), challenge e precomputed by the
// extractor: accept iff the common checks pass and jacobi(y(R)) == 1.
bool verify_one_schnorr(const uint8_t *px, const uint8_t *py,
                        const uint8_t *e32, const uint8_t *r32,
                        const uint8_t *s32) {
  Fe r;
  Pt acc;
  if (!schnorr_msm(px, py, e32, r32, s32, r, acc)) return false;
  // jacobi(y(R)) with y = Y/Z^3: jacobi(Y/Z^3) = jacobi(Y)*jacobi(Z) =
  // jacobi(Y*Z) (the symbol is multiplicative; squares vanish)
  return fe_euler_is_one(FP.mul(acc.y, acc.z));
}

// BIP340 (taproot): accept iff the common checks pass and y(R) is EVEN
// (the pubkey columns carry the lift_x'd even-y point).
bool verify_one_bip340(const uint8_t *px, const uint8_t *py,
                       const uint8_t *e32, const uint8_t *r32,
                       const uint8_t *s32) {
  Fe r;
  Pt acc;
  if (!schnorr_msm(px, py, e32, r32, s32, r, acc)) return false;
  // evenness needs the affine y = Y / Z^3
  Fe zi = FP.inv(acc.z);
  Fe zi2 = FP.sqr(zi);
  Fe y_aff = FP.mul(acc.y, FP.mul(zi2, zi));
  return (y_aff.v[0] & 1) == 0;
}

// Shared prologue of the batch verifiers: validity of each ECDSA row's s
// (Schnorr-family rows never join the inversion) and the Montgomery batch
// inversion producing w[i] = s_i^-1.  ONE definition so the serial and
// threaded entries can never diverge on the s-validity rule.
void batch_inversion_prologue(const uint8_t *s, const uint8_t *present,
                              int count, bool *s_ok, Fe *w) {
  Fe *sv = new Fe[count];
  Fe *prefix = new Fe[count];
  Fe run{{1, 0, 0, 0}};
  for (int i = 0; i < count; ++i) {
    bool schnorr = present != nullptr && present[i] >= 2;
    Fe si = fe_from_be(s + 32 * i);
    s_ok[i] = !schnorr && !(is_zero(si) || ge(si, FN.m));
    sv[i] = s_ok[i] ? si : Fe{{1, 0, 0, 0}};
    run = FN.mul(run, sv[i]);
    prefix[i] = run;
  }
  Fe inv_all = FN.inv(run);
  for (int i = count - 1; i >= 0; --i) {
    Fe before = (i == 0) ? Fe{{1, 0, 0, 0}} : prefix[i - 1];
    w[i] = FN.mul(inv_all, before);
    inv_all = FN.mul(inv_all, sv[i]);
  }
  delete[] sv;
  delete[] prefix;
}

// Verify rows [lo, hi) (shared by the serial entry and the threaded one);
// returns the number of valid rows in the range.
int secp_verify_rows(const uint8_t *px, const uint8_t *py, const uint8_t *z,
                     const uint8_t *r, const uint8_t *s,
                     const uint8_t *present, const bool *s_ok, const Fe *w,
                     int lo, int hi, uint8_t *out) {
  int valid = 0;
  for (int i = lo; i < hi; ++i) {
    bool ok;
    if (present != nullptr && present[i] == 0) {
      ok = false;
    } else if (present != nullptr && present[i] == 2) {
      ok = verify_one_schnorr(px + 32 * i, py + 32 * i, z + 32 * i,
                              r + 32 * i, s + 32 * i);
    } else if (present != nullptr && present[i] == 3) {
      ok = verify_one_bip340(px + 32 * i, py + 32 * i, z + 32 * i,
                             r + 32 * i, s + 32 * i);
    } else {
      ok = s_ok[i] && verify_one(px + 32 * i, py + 32 * i, z + 32 * i,
                                 r + 32 * i, w[i]);
    }
    out[i] = ok ? 1 : 0;
    valid += ok;
  }
  return valid;
}

}  // namespace

namespace {
void fe_to_be(const Fe &a, uint8_t *out) {
  for (int i = 0; i < 4; ++i)
    for (int j = 0; j < 8; ++j)
      out[(3 - i) * 8 + j] = (uint8_t)(a.v[i] >> (8 * (7 - j)));
}
}  // namespace

extern "C" {

// Debug/test hooks: 32-byte big-endian in/out field operations.
void secp_dbg_op(int op, const uint8_t *a32, const uint8_t *b32, uint8_t *out) {
  Fe a = fe_from_be(a32), b = fe_from_be(b32);
  Fe r{{0, 0, 0, 0}};
  switch (op) {
    case 0: r = FP.mul(a, b); break;
    case 1: r = FP.add(a, b); break;
    case 2: r = FP.sub(a, b); break;
    case 3: r = FP.inv(a); break;
    case 4: r = FN.mul(a, b); break;
    case 5: r = FN.inv(a); break;
  }
  fe_to_be(r, out);
}

// Debug: kG via the window table path; writes affine x,y (inverts Z).
void secp_dbg_mulg(const uint8_t *k32, uint8_t *x_out, uint8_t *y_out) {
  Fe k = fe_from_be(k32);
  Pt acc = Pt{{{0}}, {{1, 0, 0, 0}}, {{0}}};
  for (int w4 = 63; w4 >= 0; --w4) {
    if (!pt_inf(acc)) {
      acc = pt_double(acc);
      acc = pt_double(acc);
      acc = pt_double(acc);
      acc = pt_double(acc);
    }
    int limb = w4 / 16, shift = (w4 % 16) * 4;
    int d = (int)((k.v[limb] >> shift) & 0xF);
    if (d) acc = pt_add(acc, TAB.g[d]);
  }
  Fe zi = FP.inv(acc.z);
  Fe zi2 = FP.sqr(zi);
  fe_to_be(FP.mul(acc.x, zi2), x_out);
  fe_to_be(FP.mul(acc.y, FP.mul(zi2, zi)), y_out);
}

// Inputs: concatenated 32-byte big-endian arrays, one entry per signature.
//   px, py: affine public key coordinates
//   z: message digests (ECDSA) or precomputed challenges (Schnorr)
//   r, s: signature scalars
//   present: per-row algorithm, or NULL for all-ECDSA: 0 = auto-invalid,
//            1 = ECDSA, 2 = BCH Schnorr (RawBatch.present semantics)
// Output: out[i] = 1 if valid else 0.  Returns number of valid signatures.
int secp_verify_batch(const uint8_t *px, const uint8_t *py, const uint8_t *z,
                      const uint8_t *r, const uint8_t *s,
                      const uint8_t *present, int count, uint8_t *out) {
  bool *s_ok = new bool[count];
  Fe *w = new Fe[count];
  batch_inversion_prologue(s, present, count, s_ok, w);
  int valid = secp_verify_rows(px, py, z, r, s, present, s_ok, w, 0, count,
                               out);
  delete[] s_ok;
  delete[] w;
  return valid;
}

}  // extern "C"

// ===========================================================================
// Host-side batch preparation for the TPU kernel (tpunode/verify/kernel.py
// prepare_batch): range checks, Montgomery batch inversion of s, u1/u2,
// GLV decomposition, 4-bit window digits and radix-11 limb conversion —
// the per-item big-int work that dominates Python prep.  Layouts match
// PreparedBatch exactly (limb-major / batch-minor int32).
// ===========================================================================

#include <atomic>
#include <memory>
#include <thread>
#include <vector>

namespace {

// GLV lattice constants (standard public secp256k1 endomorphism basis;
// same values as tpunode/verify/kernel.py:71-74, verified bit-exact against
// kernel.glv_split in tests/test_native_verify.py).
const uint64_t GLV_A1[2] = {0xE86C90E49284EB15ULL, 0x3086D221A7D46BCDULL};
const uint64_t GLV_B1N[2] = {0x6F547FA90ABFE4C3ULL, 0xE4437ED6010E8828ULL};
const uint64_t GLV_A2[3] = {0x57C1108D9D44CFD8ULL, 0x14CA50F7A8E2F3F6ULL, 1ULL};
// b2 == a1

constexpr int PREP_RADIX = 11;
constexpr int PREP_NLIMBS = 24;
// windows per window width: 33 x 4-bit (default), 27 x 5-bit (ISSUE 13)

// ---- fixed-width helpers on little-endian u64 arrays ----------------------

// out[no] = a[na] * b[nb] (no >= na+nb)
inline void mp_mul(const uint64_t *a, int na, const uint64_t *b, int nb,
                   uint64_t *out, int no) {
  for (int i = 0; i < no; ++i) out[i] = 0;
  for (int i = 0; i < na; ++i) {
    uint64_t carry = 0;
    for (int j = 0; j < nb; ++j) {
      u128 cur = (u128)a[i] * b[j] + out[i + j] + carry;
      out[i + j] = (uint64_t)cur;
      carry = (uint64_t)(cur >> 64);
    }
    int k = i + nb;
    while (carry && k < no) {
      u128 cur = (u128)out[k] + carry;
      out[k] = (uint64_t)cur;
      carry = (uint64_t)(cur >> 64);
      ++k;
    }
  }
}

// a[n] += b[nb]; returns carry out
inline uint64_t mp_add(uint64_t *a, int n, const uint64_t *b, int nb) {
  uint64_t carry = 0;
  for (int i = 0; i < n; ++i) {
    u128 cur = (u128)a[i] + (i < nb ? b[i] : 0) + carry;
    a[i] = (uint64_t)cur;
    carry = (uint64_t)(cur >> 64);
  }
  return carry;
}

// a[n] -= b[nb]; returns borrow out
inline uint64_t mp_sub(uint64_t *a, int n, const uint64_t *b, int nb) {
  uint64_t borrow = 0;
  for (int i = 0; i < n; ++i) {
    uint64_t bi = i < nb ? b[i] : 0;
    u128 d = (u128)a[i] - bi - borrow;
    a[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return borrow;
}

// Barrett reciprocals round(2^384 * b / n) for b = b2(=a1) and |b1| —
// the same constants as libsecp256k1's scalar_split_lambda g1/g2 and
// kernel.py's _G1/_G2 (bit-identical digits across all three).
const uint64_t GLV_G1[4] = {0xE893209A45DBB031ULL, 0x3DAA8A1471E8CA7FULL,
                            0xE86C90E49284EB15ULL, 0x3086D221A7D46BCDULL};
const uint64_t GLV_G2[4] = {0x1571B4AE8AC47F71ULL, 0x221208AC9DF506C6ULL,
                            0x6F547FA90ABFE4C4ULL, 0xE4437ED6010E8828ULL};

// c = round(k * g / 2^384): one 4x4 multiply + a shifted rounding add.
inline void glv_c(const uint64_t g[4], const Fe &k, uint64_t c[3]) {
  uint64_t t[8];
  mp_mul(k.v, 4, g, 4, t, 8);
  uint64_t half[6] = {0, 0, 0, 0, 0, 0x8000000000000000ULL};  // 2^383
  mp_add(t, 8, half, 6);
  c[0] = t[6];
  c[1] = t[7];
  c[2] = 0;
}

// signed k1/k2 halves: value = (-1)^neg * abs[3]
struct Half {
  uint64_t abs[3];
  bool neg;
};

// k1 = k - c1*a1 - c2*a2 ; k2 = c1*b1n - c2*b2  (b1 = -b1n, b2 = a1),
// computed in 448-bit two's complement.
inline void glv_halves(const Fe &k, const uint64_t c1[3], const uint64_t c2[3],
                       Half &h1, Half &h2) {
  uint64_t acc[7] = {k.v[0], k.v[1], k.v[2], k.v[3], 0, 0, 0};
  uint64_t t[7];
  mp_mul(c1, 3, GLV_A1, 2, t, 7);
  mp_sub(acc, 7, t, 7);
  mp_mul(c2, 3, GLV_A2, 3, t, 7);
  mp_sub(acc, 7, t, 7);
  h1.neg = (acc[6] >> 63) != 0;
  if (h1.neg) {  // negate two's complement
    for (int i = 0; i < 7; ++i) acc[i] = ~acc[i];
    uint64_t one[1] = {1};
    mp_add(acc, 7, one, 1);
  }
  h1.abs[0] = acc[0]; h1.abs[1] = acc[1]; h1.abs[2] = acc[2];

  uint64_t acc2[7] = {0, 0, 0, 0, 0, 0, 0};
  mp_mul(c1, 3, GLV_B1N, 2, acc2, 7);
  mp_mul(c2, 3, GLV_A1, 2, t, 7);  // b2 == a1
  mp_sub(acc2, 7, t, 7);
  h2.neg = (acc2[6] >> 63) != 0;
  if (h2.neg) {
    for (int i = 0; i < 7; ++i) acc2[i] = ~acc2[i];
    uint64_t one[1] = {1};
    mp_add(acc2, 7, one, 1);
  }
  h2.abs[0] = acc2[0]; h2.abs[1] = acc2[1]; h2.abs[2] = acc2[2];
}

// MSB-first wb-bit window digits of abs into out[w * size + lane].
// 4-bit digits never straddle 64-bit word edges; 5-bit digits (ISSUE 13:
// window_bits=5, 27 windows) can, so the straddle path ORs in the next
// word's low bits — bit-identical to kernel.py's _ints_to_digits_np.
inline void write_digits(const uint64_t abs[3], int32_t *out, int size,
                         int lane, int wb, int nwin) {
  const uint64_t mask = (1u << wb) - 1;
  for (int w = 0; w < nwin; ++w) {
    int sh = wb * (nwin - 1 - w);
    int word = sh / 64, off = sh % 64;
    uint64_t lo = abs[word] >> off;
    if (off > 64 - wb && word + 1 < 3) lo |= abs[word + 1] << (64 - off);
    out[w * size + lane] = (int32_t)(lo & mask);
  }
}

// radix-11 little-endian limbs of a (canonical) into out[j * size + lane].
inline void write_limbs(const Fe &a, int32_t *out, int size, int lane) {
  for (int j = 0; j < PREP_NLIMBS; ++j) {
    int sh = PREP_RADIX * j;
    int w = sh / 64, off = sh % 64;
    uint64_t lo = a.v[w] >> off;
    if (off > 64 - PREP_RADIX && w + 1 < 4) lo |= a.v[w + 1] << (64 - off);
    out[j * size + lane] = (int32_t)(lo & ((1u << PREP_RADIX) - 1));
  }
}

}  // namespace

extern "C" {

// Threaded batch verify for multi-core hosts: same semantics as
// secp_verify_batch, rows split across ``nthreads`` (0 = hardware
// concurrency).  The Montgomery inversion stays serial (it is ~0.1% of
// the work); each MSM row is independent.
int secp_verify_batch_mt(const uint8_t *px, const uint8_t *py,
                         const uint8_t *z, const uint8_t *r, const uint8_t *s,
                         const uint8_t *present, int count, uint8_t *out,
                         int nthreads) {
  int T = nthreads > 0 ? nthreads : (int)std::thread::hardware_concurrency();
  if (T < 1) T = 1;
  if (T == 1 || count < 64)
    return secp_verify_batch(px, py, z, r, s, present, count, out);

  std::vector<Fe> w(count);
  std::unique_ptr<bool[]> s_ok(new bool[count]);
  batch_inversion_prologue(s, present, count, s_ok.get(), w.data());

  std::atomic<int> valid{0};
  std::vector<std::thread> ts;
  int chunk = (count + T - 1) / T;
  for (int t = 0; t < T; ++t) {
    int lo = t * chunk, hi = lo + chunk < count ? lo + chunk : count;
    if (lo >= hi) break;
    ts.emplace_back([&, lo, hi]() {
      valid.fetch_add(
          secp_verify_rows(px, py, z, r, s, present, s_ok.get(), w.data(),
                           lo, hi, out),
          std::memory_order_relaxed);
    });
  }
  for (auto &th : ts) th.join();
  return valid.load();
}

// Host prep for one device batch.  All byte inputs are 32-byte big-endian,
// one entry per item; ``present[i]`` carries the RawBatch algorithm code
// (0 = absent, 1 = ECDSA, 2 = BCH Schnorr — for Schnorr, ``z`` is the
// precomputed challenge e, u1 = s and u2 = n - e need no inversion, and
// ``r`` is an Fp x-coordinate with no r+n candidate).  int32 outputs are
// (rows, size) C-contiguous, zero-initialized by the caller; lanes >= count
// stay zero.  ``window_bits`` selects the digit layout: 4 (33 windows,
// the default) or 5 (27 windows, ISSUE 12/13 — the digit arrays must be
// allocated 27 rows tall).  Returns the number of GLV bound violations
// (0 = success; cannot occur for in-range scalars — a nonzero return
// means a bug and the caller must refuse the batch), or -1 for an
// unsupported window width.
int secp_prepare_batch_w(const uint8_t *px, const uint8_t *py,
                         const uint8_t *z, const uint8_t *r, const uint8_t *s,
                         const uint8_t *present, int count, int size,
                         int32_t *d1a, int32_t *d1b, int32_t *d2a,
                         int32_t *d2b, uint8_t *negs, int32_t *qx,
                         int32_t *qy, int32_t *r1, int32_t *r2,
                         uint8_t *r2_valid, uint8_t *host_valid,
                         uint8_t *schnorr, uint8_t *bip340, int nthreads,
                         int window_bits) {
  int nwin;
  if (window_bits == 4) {
    nwin = 33;
  } else if (window_bits == 5) {
    nwin = 27;
  } else {
    return -1;
  }
  const int bound_shift = window_bits * nwin - 128;  // 4 (w4) / 7 (w5)
  // ---- serial: validity + Montgomery batch inversion of s (ECDSA rows) ----
  std::vector<Fe> sv(count), prefix(count), w(count);
  std::vector<uint8_t> ok(count), is_sch(count);
  Fe run{{1, 0, 0, 0}};
  for (int i = 0; i < count; ++i) {
    Fe si = fe_from_be(s + 32 * i);
    Fe ri = fe_from_be(r + 32 * i);
    is_sch[i] = present[i] >= 2;  // both Schnorr variants: u1=s, u2=n-e
    if (is_sch[i]) {
      // spec ranges: r < p, s < n; zero allowed for both
      ok[i] = !ge(si, FN.m) && !ge(ri, FP.m);
      sv[i] = Fe{{1, 0, 0, 0}};  // no inversion needed
    } else {
      ok[i] = present[i] && !is_zero(si) && !ge(si, FN.m) && !is_zero(ri) &&
              !ge(ri, FN.m);
      sv[i] = ok[i] ? si : Fe{{1, 0, 0, 0}};
    }
    run = FN.mul(run, sv[i]);
    prefix[i] = run;
  }
  Fe inv_all = FN.inv(run);
  for (int i = count - 1; i >= 0; --i) {
    Fe before = (i == 0) ? Fe{{1, 0, 0, 0}} : prefix[i - 1];
    w[i] = FN.mul(inv_all, before);
    inv_all = FN.mul(inv_all, sv[i]);
  }

  // ---- parallel: per-item GLV + digits + limbs ----
  std::atomic<int> violations{0};
  auto work = [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      if (!ok[i]) continue;
      host_valid[i] = 1;
      Fe zi = fe_from_be(z + 32 * i);
      while (ge(zi, FN.m)) sub_mod_raw(zi, FN.m);
      Fe ri = fe_from_be(r + 32 * i);
      Fe u1, u2;
      if (is_sch[i]) {
        (present[i] == 2 ? schnorr : bip340)[i] = 1;
        u1 = fe_from_be(s + 32 * i);  // u1 = s (< n, checked)
        u2 = Fe{{0, 0, 0, 0}};        // u2 = n - e (mod n)
        if (!is_zero(zi)) {
          u2 = Fe{{FN.m[0], FN.m[1], FN.m[2], FN.m[3]}};
          sub_mod_raw(u2, zi.v);
        }
      } else {
        u1 = FN.mul(zi, w[i]);
        u2 = FN.mul(ri, w[i]);
      }
      Half h[4];
      uint64_t c1[3], c2[3];
      glv_c(GLV_G1, u1, c1);
      glv_c(GLV_G2, u1, c2);
      glv_halves(u1, c1, c2, h[0], h[1]);
      glv_c(GLV_G1, u2, c1);
      glv_c(GLV_G2, u2, c2);
      glv_halves(u2, c1, c2, h[2], h[3]);
      int32_t *dsts[4] = {d1a, d1b, d2a, d2b};
      for (int j = 0; j < 4; ++j) {
        // |k| >= 2^(wb*nwin): outside the window range (2^132 at w4,
        // 2^135 at w5)
        if (h[j].abs[2] >> bound_shift) {
          violations.fetch_add(1, std::memory_order_relaxed);
          continue;
        }
        write_digits(h[j].abs, dsts[j], size, i, window_bits, nwin);
        negs[j * size + i] = h[j].neg ? 1 : 0;
      }
      write_limbs(fe_from_be(px + 32 * i), qx, size, i);
      write_limbs(fe_from_be(py + 32 * i), qy, size, i);
      write_limbs(ri, r1, size, i);
      // r + n < p ?  (ECDSA-only: Schnorr compares x(R) to r over Fp)
      if (!is_sch[i]) {
        Fe rn = ri;
        uint64_t carry = mp_add(rn.v, 4, FN.m, 4);
        if (!carry && !ge(rn, FP.m)) {
          write_limbs(rn, r2, size, i);
          r2_valid[i] = 1;
        }
      }
    }
  };
  int T = nthreads > 0 ? nthreads : (int)std::thread::hardware_concurrency();
  if (T < 1) T = 1;
  if (T == 1 || count < 256) {
    work(0, count);
  } else {
    std::vector<std::thread> ts;
    int chunk = (count + T - 1) / T;
    for (int t = 0; t < T; ++t) {
      int lo = t * chunk, hi = lo + chunk < count ? lo + chunk : count;
      if (lo >= hi) break;
      ts.emplace_back(work, lo, hi);
    }
    for (auto &th : ts) th.join();
  }
  return violations.load();
}

// Legacy 4-bit entry point (kept so an older binding keeps working).
int secp_prepare_batch(const uint8_t *px, const uint8_t *py, const uint8_t *z,
                       const uint8_t *r, const uint8_t *s,
                       const uint8_t *present, int count, int size,
                       int32_t *d1a, int32_t *d1b, int32_t *d2a, int32_t *d2b,
                       uint8_t *negs, int32_t *qx, int32_t *qy, int32_t *r1,
                       int32_t *r2, uint8_t *r2_valid, uint8_t *host_valid,
                       uint8_t *schnorr, uint8_t *bip340, int nthreads) {
  return secp_prepare_batch_w(px, py, z, r, s, present, count, size, d1a, d1b,
                              d2a, d2b, negs, qx, qy, r1, r2, r2_valid,
                              host_valid, schnorr, bip340, nthreads, 4);
}

}  // extern "C"
