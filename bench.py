"""Driver benchmark: batch ECDSA verify throughput on one chip.

Measures the north-star metric (BASELINE.json): sig-verifies/sec/chip of
the TPU kernel at the standard batch size (4096), against the single-core
CPU baseline (the C++ batch verifier in native/secp256k1, the stand-in for
single-core libsecp256k1).  Prints exactly ONE JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Robustness contract (VERDICT round 1, item 1b): TPU backend init on this
box can hang or fail, so the device benchmark runs in a watchdog-bounded
subprocess — one retry on failure, then a clearly-labeled cpu-jax
fallback — and the parent process NEVER imports jax.  Whatever happens,
the final line is valid single-line JSON with a numeric ``value``.

Run from the repo root: python bench.py
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

BATCH = int(os.environ.get("TPUNODE_BENCH_BATCH", 32768))
UNIQUE = min(512, BATCH)  # unique sigs, tiled to BATCH (device work identical)
TIMED_ITERS = int(os.environ.get("TPUNODE_BENCH_ITERS", 5))
CPU_SAMPLE = min(256, BATCH)
# Watchdog budgets (seconds): first device attempt, retry, cpu-jax fallback.
# The Pallas compile takes ~36s on a healthy tunnel but the axon backend
# compiles server-side, where a backlog can stretch it to minutes — budget
# generously; a kill cannot cancel the server-side compile anyway (the
# retry then usually finds it warm).
T_FIRST = float(os.environ.get("TPUNODE_BENCH_TIMEOUT", 420))
T_RETRY = float(os.environ.get("TPUNODE_BENCH_RETRY_TIMEOUT", 240))
T_FALLBACK = float(os.environ.get("TPUNODE_BENCH_FALLBACK_TIMEOUT", 150))


def _worker() -> None:
    """Device benchmark body; runs in a bounded subprocess.

    Prints one JSON line: {"ok": true, rate, device, step_ms, compile_s}
    or {"ok": false, "error": ...}.  May hang or die on backend init —
    the parent's watchdog handles that.
    """
    def progress(msg: str) -> None:
        # stderr so a parent timeout can report WHAT the worker was doing
        print(f"[bench-worker] {msg}", file=sys.stderr, flush=True)

    try:
        import jax
        import jax.numpy as jnp

        if os.environ.get("TPUNODE_BENCH_FORCE_CPU"):
            # Env alone is not enough: this box's TPU shim (sitecustomize)
            # force-sets jax_platforms="axon,cpu" in every process.
            jax.config.update("jax_platforms", "cpu")

        # Persistent compilation cache: a retry (or a bench after the test
        # suite / engine warmup) reuses the first successful compile.
        from tpunode.verify.engine import enable_compile_cache

        enable_compile_cache()

        from benchmarks.common import device_kind, make_triples, tile
        from tpunode.verify.ecdsa_cpu import verify_batch_cpu
        from tpunode.verify.kernel import (
            _pallas_usable,
            prepare_batch,
            verify_device,
        )

        t0 = time.perf_counter()
        dev = jax.devices()[0]  # first backend touch — may block
        init_s = time.perf_counter() - t0
        progress(f"backend up: {dev} in {init_s:.1f}s")

        if _pallas_usable(BATCH):
            from tpunode.verify.pallas_kernel import verify_blocked as device_fn

            kernel_name = "pallas"
        else:
            device_fn = verify_device
            kernel_name = "xla"

        base = make_triples(UNIQUE)
        items = tile(base, BATCH)
        prep = prepare_batch(items, pad_to=BATCH)
        args = tuple(jax.device_put(jnp.asarray(a), dev) for a in prep.device_args)
        progress(f"host prep done, compiling {kernel_name} at batch {BATCH}...")
        t0 = time.perf_counter()
        out = device_fn(*args)  # compile + first run
        # ONE bulk transfer (collect_verdicts): iterating the device array
        # would issue one tunnel round-trip PER ELEMENT — minutes at batch
        # 32k; that, not compile time, was what blew the r01/r02 watchdogs.
        from tpunode.verify.kernel import collect_verdicts

        got = collect_verdicts(out, len(base))
        compile_s = time.perf_counter() - t0
        progress(f"compiled+ran in {compile_s:.1f}s, checking oracle...")
        # Expectation via the C++ engine (itself pinned against the Python
        # oracle in tests): the pure-Python oracle needs ~1 min for 512 sigs
        # on a busy 1-core host, which has blown retry watchdogs before.
        from tpunode.verify.cpu_native import load_native_verifier

        native = load_native_verifier()
        expect = (
            native.verify_batch(base)
            if native is not None
            else verify_batch_cpu(base)
        )
        if got != expect:
            # fatal: kernel correctness bug, not an infra flake — the parent
            # must not retry or mask this with the cpu fallback.
            print(
                json.dumps(
                    {"ok": False, "fatal": True,
                     "error": "device/oracle verdict mismatch"}
                )
            )
            return

        from tpunode.trace import profile_to

        times = []
        with profile_to(os.environ.get("TPUNODE_PROFILE")):
            for _ in range(TIMED_ITERS):
                t0 = time.perf_counter()
                device_fn(*args).block_until_ready()
                times.append(time.perf_counter() - t0)
        dt = statistics.median(times)
        print(
            json.dumps(
                {
                    "ok": True,
                    "rate": BATCH / dt,
                    "device": device_kind(),
                    "kernel": kernel_name,
                    "step_ms": round(dt * 1e3, 3),
                    "compile_s": round(compile_s, 1),
                    "init_s": round(init_s, 1),
                }
            )
        )
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _run_worker(timeout: float, env_extra: dict | None = None) -> dict:
    """Run the device bench in a subprocess; parse its last JSON line.

    The worker runs in its own process group and the whole group is killed
    on timeout: the TPU shim may spawn helpers that inherit the stdout
    pipe, and killing only the direct child would leave communicate()
    blocked on them forever.
    """
    env = dict(os.environ)
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        try:
            _, stderr = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            stderr = ""
        # the worker streams progress to stderr; surface its last line so a
        # timeout says what the worker was doing when the axe fell
        last = ""
        for line in (stderr or "").splitlines():
            if line.startswith("[bench-worker]"):
                last = line
        return {
            "ok": False,
            "error": f"device bench timed out after {timeout:.0f}s"
            + (f" (last: {last})" if last else ""),
        }
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {
        "ok": False,
        "error": f"worker rc={proc.returncode}, no JSON "
        f"(stderr tail: {stderr[-300:]!r})",
    }


def _kill_group(proc: subprocess.Popen) -> None:
    import signal

    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        proc.kill()


def main() -> None:
    # CPU single-core baseline first: jax-free, can't hang on TPU init.
    from benchmarks.common import cpu_single_core_bench, make_triples

    base = make_triples(UNIQUE)
    cpu_rate, cpu_engine, _ = cpu_single_core_bench(base[:CPU_SAMPLE])

    res = _run_worker(T_FIRST)
    first_err = None if res.get("ok") else res.get("error", "?")
    if not res.get("ok") and not res.get("fatal"):
        res = _run_worker(T_RETRY)
    if not res.get("ok") and not res.get("fatal"):
        # Clearly-labeled cpu-jax fallback so the driver still records a
        # numeric value; ``device`` says cpu:* and tpu_error says why.
        tpu_err = res.get("error", "?")
        res = _run_worker(
            T_FALLBACK,
            {
                "JAX_PLATFORMS": "cpu",
                "TPUNODE_BENCH_FORCE_CPU": "1",
                "TPUNODE_BENCH_ITERS": "2",
            },
        )
        res["tpu_error"] = tpu_err
    if first_err is not None:
        res["first_error"] = first_err

    out = {
        "metric": "sig_verify_throughput",
        "value": round(res.get("rate", 0.0), 1),
        "unit": "sigs/sec/chip",
        "vs_baseline": round(res.get("rate", 0.0) / cpu_rate, 2),
        "device": res.get("device", "unavailable"),
        "baseline_cpu_single_core": round(cpu_rate, 1),
        "baseline_engine": cpu_engine,
        "batch": BATCH,
    }
    for k in ("step_ms", "compile_s", "init_s", "tpu_error", "error", "first_error"):
        if k in res:
            out[k] = res[k]
    print(json.dumps(out))
    if res.get("fatal"):
        sys.exit(1)  # kernel correctness failure must not look like success


if __name__ == "__main__":
    if "--worker" in sys.argv:
        _worker()
    else:
        main()
