"""Driver benchmark: batch ECDSA verify throughput on one chip.

Measures the north-star metric (BASELINE.json): sig-verifies/sec/chip of
the TPU kernel against the single-core CPU baseline (the C++ batch
verifier in native/secp256k1, the stand-in for single-core libsecp256k1).
Prints exactly ONE JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Robustness contract (VERDICT r1 item 1b, r3 weak #1 — this box's TPU
tunnel can be down or minutes-slow at any given moment, and rounds 1-3
each lost their headline number to a different flavor of that):

* the parent process NEVER imports jax; every device step runs in a
  watchdog-bounded subprocess (process group killed on timeout);
* a cheap PROBE subprocess first checks that the backend initializes at
  all and reports its platform — if the tunnel is dead we fail fast
  instead of burning the whole budget on big-batch attempts;
* the TPU attempt then DEGRADES adaptively: pallas@32768 ->
  pallas@8192 -> 4096 (pallas on TPU, never an XLA compile above 4096
  inside a watchdog) — each attempt reuses the persistent compile cache,
  so a killed-but-server-side-finished compile makes the next attempt
  cheap;
* kernel choice comes from jax.devices()[0].platform (not
  jax.default_backend(), which this box's axon shim can leave at a
  stale value);
* if no TPU attempt lands, the freshest in-round device measurement
  persisted by the round-long watcher (benchmarks/watcher.py ->
  benchmarks/device_runs.jsonl) is reported with explicit provenance
  ("in-round-watcher" + timestamp) — one-shot sampling of a flaky
  tunnel was the round-1..4 failure mode;
* only if no in-round device sample exists either, a clearly-labeled
  cpu-jax fallback (small batch, XLA) still produces a numeric value
  with the TPU error noted.

* the TPU ladder carries XLA-kernel fallback rungs (and a MosaicError
  fast-skip) for the r5-observed outage mode where the axon Mosaic
  compile helper 500s on every pallas program while plain XLA works;
* the whole ladder runs under a hard T_LADDER_TOTAL ceiling (600s)
  regardless of rung count.

Whatever happens, the final line is valid single-line JSON with a
numeric ``value``.  Worst-case wall clock ~15.5 min (probe 120s +
ladder 600s + cpu fallback 210s); round 3's artifact demonstrated the
driver tolerating 810s (BENCH_r03.json, rc=0) and the watcher fallback
makes a fully-exhausted ladder the rare path.

Run from the repo root: python bench.py
"""

from __future__ import annotations

import calendar
import json
import os
import re
import statistics
import sys
import time

BATCH = int(os.environ.get("TPUNODE_BENCH_BATCH", 32768))
UNIQUE = 512
TIMED_ITERS = int(os.environ.get("TPUNODE_BENCH_ITERS", 5))
CPU_SAMPLE = 256

# Watchdog budgets (seconds).  The axon backend compiles server-side and a
# kill cannot cancel the server-side work — the next attempt usually finds
# it warm (and the persistent cache makes warm == fast).
T_PROBE = float(os.environ.get("TPUNODE_BENCH_PROBE_TIMEOUT", 120))
# (batch, budget, kernel): kernel None = auto (pallas on TPU); "xla"
# forces the portable XLA program — the working path when the axon
# Mosaic compile helper is broken (observed r5) but the device is up.
LADDER = (
    (BATCH, float(os.environ.get("TPUNODE_BENCH_TIMEOUT", 270)), None),
    (8192, float(os.environ.get("TPUNODE_BENCH_RETRY_TIMEOUT", 150)), None),
    (4096, 120.0, None),
    (8192, 180.0, "xla"),
    (4096, 150.0, "xla"),
)
# The cpu-jax fallback's XLA compile at batch 2048 takes ~100-170s cold
# (the kernel now carries two constant-exponent pows besides the MSM);
# .jax_cache is pre-warmed in-round, but budget for a cold cache anyway.
T_FALLBACK = float(os.environ.get("TPUNODE_BENCH_FALLBACK_TIMEOUT", 210))
# Mempool-ingest scenario (ISSUE 5): jax is imported (Node pulls the
# engine) but never the device — the oracle backend verifies on the CPU,
# so the budget covers interpreter+jax import plus a few seconds of
# pure-Python signature verification.
T_MEMPOOL = float(os.environ.get("TPUNODE_BENCH_MEMPOOL_TIMEOUT", 150))
# Chaos resilience scenario (ISSUE 7): a seeded fault plan against a
# full node with a SIMULATED device (instant warmup, host-computed
# verdicts on the genuine tpu rung) — jax imported, tunnel never
# touched.  Budget shaped like the mempool scenario's.
T_CHAOS = float(os.environ.get("TPUNODE_BENCH_CHAOS_TIMEOUT", 150))
# Kernel point-form A/B (ISSUE 8): projective vs affine step time on
# cpu-jax, per batch size.  Batch 1024 fits its budget once the
# persistent compile cache is warm (two cold XLA compiles ~2x90s + 10
# timed steps ~35s; a cold-cache round may label it timed-out — never
# masking the headline).  Batch 32768 is DISABLED by default: the
# repo's watchdog discipline forbids host-side XLA compiles above 4096
# (compile grows super-linearly — blew r02/r03), and a single cpu-jax
# step at 32768 is ~2 min, so median-of-5 for two forms cannot fit any
# driver budget; set TPUNODE_BENCH_KERNELAB_BIG_TIMEOUT > 0 to attempt
# (PERF.md records a manual no-watchdog run at both batches instead).
T_KERNEL_AB = float(os.environ.get("TPUNODE_BENCH_KERNELAB_TIMEOUT", 270))
T_KERNEL_AB_BIG = float(
    os.environ.get("TPUNODE_BENCH_KERNELAB_BIG_TIMEOUT", 0)
)
# Crash-recovery scenario (ISSUE 9): reopen/replay latency vs log size,
# compaction pause, and a bounded kill-torture sweep (real writer-child
# subprocesses killed at seeded points).  jax never imported.
T_RECOVERY = float(os.environ.get("TPUNODE_BENCH_RECOVERY_TIMEOUT", 180))
# Streaming-pipeline A/B (ISSUE 10): the duplicate-heavy mempool
# firehose against a full Node on the cpu proxy (native CPU verify
# engine — the tunnel is never touched), run serial
# (pipeline_depth=1, extract_workers=1) then pipelined (depth 2,
# min(4, cpu) extract workers), plus an extraction-only worker scaling
# curve.  jax is never imported (backend="cpu" loads only the native
# verifier).
T_PIPELINE = float(os.environ.get("TPUNODE_BENCH_PIPELINE_TIMEOUT", 240))
# Long-IBD replay (ISSUE 11): the fetch-planner A/B (native sharded
# ingest + C++ UTXO connect vs the serial all-Python baseline) plus the
# kill -9 mid-sync leg, over persistent LogKV stores.  jax is never
# imported (backend="cpu" loads only the native verifier).
T_IBD = float(os.environ.get("TPUNODE_BENCH_IBD_TIMEOUT", 420))
# Pod-scale fleet-dispatcher scaling (ISSUE 13): 1/2/4/8-way sharding on
# the cpu-native proxy plus the campaign bit-identity pass.
T_MESH = float(os.environ.get("TPUNODE_BENCH_MESH_TIMEOUT", 300))
# Host-affine feed A/B (ISSUE 19): two 4-way e2e legs (affine vs
# central-feed baseline) plus the campaign pass through the affine
# path, all on the cpu-native proxy.
T_MESH_E2E = float(os.environ.get("TPUNODE_BENCH_MESH_E2E_TIMEOUT", 240))
# Observability overhead (ISSUE 16): timeline-sampler tick cost and
# flight-recorder bundle build, measured over a synthetic registry.
# jax is never imported (timeseries/blackbox are stdlib-only).
T_OBS = float(os.environ.get("TPUNODE_BENCH_OBS_TIMEOUT", 90))
# Multi-tenant serve firehose (ISSUE 20): >=1000 real-socket clients,
# Zipf duplicates, the induced-burn shed leg and the receipt audit, on
# the cpu-native proxy (jax is never imported).
T_SERVE = float(os.environ.get("TPUNODE_BENCH_SERVE_TIMEOUT", 240))
# Total ceiling: probe (<=120s) + ladder (<=600s) + fallback (<=210s)
# + mempool (<=150s) keeps the worst case ~18 min; r03's artifact
# demonstrated the driver tolerating 810s, and the in-round watcher
# fallback makes a fully-exhausted ladder the rare path, not the
# common one.
T_LADDER_TOTAL = float(os.environ.get("TPUNODE_BENCH_LADDER_TOTAL", 600))


def _progress(msg: str) -> None:
    # stderr so a parent timeout can report WHAT the worker was doing
    print(f"[bench-worker] {msg}", file=sys.stderr, flush=True)


def _sanitizer_counts(event_counts: dict, metrics) -> dict:
    """asyncsan/threadsan/watchdog regression signals for the BENCH JSON
    (ISSUE 3 + 18 satellites): leaked supervised tasks, watchdog stall
    episodes, and the lock sanitizer's cycle/reentry/hold watermarks seen
    by this process.  A nonzero trajectory across rounds flags a
    concurrency regression the throughput number alone would hide.  The
    threadsan keys are registry counters (not event counts) so they are
    meaningful whether or not TPUNODE_THREADSAN armed this run — zeros
    when off."""
    from tpunode.threadsan import registry as _ts

    return {
        "task_leak": int(event_counts.get("asyncsan.task_leak", 0)),
        "watchdog_stall": int(event_counts.get("watchdog.stall", 0)),
        "task_leaks_metric": metrics.get("asyncsan.task_leaks"),
        "lock_cycles": int(_ts.lock_cycles),
        "lock_reentries": int(_ts.lock_reentries),
        "max_hold_ms": round(_ts.max_hold_seconds * 1000.0, 3),
    }


def _worker_probe() -> None:
    """Tiny backend probe: init + platform + one trivial op.  Prints one
    JSON line; may block forever on a dead tunnel (parent watchdog)."""
    try:
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        _progress("probing backend (jax.devices may block)...")
        dev = jax.devices()[0]
        init_s = time.perf_counter() - t0
        _progress(f"backend up: {dev} in {init_s:.1f}s")
        t0 = time.perf_counter()
        val = int(jnp.arange(8).sum())
        op_s = time.perf_counter() - t0
        print(
            json.dumps(
                {
                    "ok": val == 28,
                    "platform": getattr(dev, "platform", "?"),
                    "device_kind": getattr(dev, "device_kind", "?"),
                    "init_s": round(init_s, 1),
                    "op_s": round(op_s, 1),
                }
            )
        )
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_bench() -> None:
    """Device benchmark body; runs in a bounded subprocess.

    Env contract (set by the parent):
      TPUNODE_BENCH_BATCH        padded batch size
      TPUNODE_BENCH_REQUIRE_TPU  "1": fail fast unless platform == tpu
      TPUNODE_BENCH_FORCE_CPU    "1": pin jax to cpu (labeled fallback)

    Prints one JSON line: {"ok": true, rate, device, kernel, step_ms,
    compile_s, init_s} or {"ok": false, "error": ...} (+"fatal" on a
    verdict mismatch, which the parent must not retry or mask).
    """
    batch = int(os.environ.get("TPUNODE_BENCH_BATCH", BATCH))
    require_tpu = os.environ.get("TPUNODE_BENCH_REQUIRE_TPU") == "1"
    iters = int(os.environ.get("TPUNODE_BENCH_ITERS", TIMED_ITERS))
    try:
        import jax
        import jax.numpy as jnp

        if os.environ.get("TPUNODE_BENCH_FORCE_CPU"):
            # Env alone is not enough: this box's TPU shim (sitecustomize)
            # force-sets jax_platforms in every process.
            jax.config.update("jax_platforms", "cpu")

        # Persistent compilation cache: a retry (or a bench after the test
        # suite / engine warmup) reuses the first successful compile.
        from tpunode.verify.engine import enable_compile_cache

        enable_compile_cache()

        t0 = time.perf_counter()
        _progress("initializing backend (jax.devices may block)...")
        dev = jax.devices()[0]  # first backend touch — may block
        init_s = time.perf_counter() - t0
        platform = getattr(dev, "platform", "?")
        _progress(f"backend up: {dev} in {init_s:.1f}s")
        if require_tpu and platform != "tpu":
            print(
                json.dumps(
                    {"ok": False, "error": f"platform is {platform!r}, not tpu"}
                )
            )
            return

        # Kernel selection from the actual device platform (VERDICT r3
        # item 1): pallas on TPU; the portable XLA program elsewhere —
        # and NEVER a host-side XLA compile above batch 4096 inside a
        # watchdog (its compile time grows super-linearly and blew
        # r02/r03 runs; TPU compiles run server-side and scale fine).
        # TPUNODE_BENCH_KERNEL=xla forces the XLA program on TPU — the
        # fallback for a Mosaic/remote-compile outage (observed r5: the
        # axon compile helper 500s on the pallas kernel while plain XLA
        # programs compile and run).
        from tpunode.verify.pallas_kernel import BLOCK
        from tpunode.verify.kernel import (
            collect_verdicts,
            prepare_batch,
            verify_device,
        )

        force_kernel = os.environ.get("TPUNODE_BENCH_KERNEL")
        if (
            platform == "tpu"
            and batch % BLOCK == 0
            and force_kernel != "xla"
        ):
            from tpunode.verify.pallas_kernel import verify_blocked as device_fn

            kernel_name = "pallas"
        else:
            if batch > 4096 and platform != "tpu":
                _progress(f"clamping XLA batch {batch} -> 4096")
                batch = 4096
            device_fn = verify_device
            kernel_name = "xla"

        from benchmarks.common import device_kind, make_triples, tile
        from tpunode.verify import field as _field
        from tpunode.verify import kernel as _kernel_mod
        from tpunode.verify.curve import point_form as _point_form
        from tpunode.verify.ecdsa_cpu import verify_batch_cpu

        base = make_triples(min(UNIQUE, batch))
        items = tile(base, batch)
        prep = prepare_batch(items, pad_to=batch)
        args = tuple(jax.device_put(jnp.asarray(a), dev) for a in prep.device_args)
        # The headline workload is ECDSA-only (mirrors the C++ baseline's
        # items): the pallas variant with the acceptance pows pruned at
        # trace time is the honest program for it (same one the engine
        # dispatches for ECDSA-only chunks).
        kw = (
            {"schnorr_free": prep.schnorr_free}
            if kernel_name == "pallas" else {}
        )
        _progress(f"host prep done, compiling {kernel_name} at batch {batch}...")
        t0 = time.perf_counter()
        out = device_fn(*args, **kw)  # compile + first run
        # ONE bulk transfer (collect_verdicts): iterating the device array
        # would issue one tunnel round-trip PER ELEMENT — minutes at batch
        # 32k; that, not compile time, blew the r01/r02 watchdogs.
        got = collect_verdicts(out, len(base))
        compile_s = time.perf_counter() - t0
        _progress(f"compiled+ran in {compile_s:.1f}s, checking oracle...")
        # Expectation via the C++ engine (itself pinned against the Python
        # oracle in tests): the pure-Python oracle needs ~1 min for 512
        # sigs on a busy 1-core host, which has blown watchdogs before.
        from tpunode.verify.cpu_native import load_native_verifier

        native = load_native_verifier()
        expect = (
            native.verify_batch(base)
            if native is not None
            else verify_batch_cpu(base)
        )
        if got != expect:
            # fatal: kernel correctness bug, not an infra flake — the
            # parent must not retry or mask this with the cpu fallback.
            print(
                json.dumps(
                    {"ok": False, "fatal": True,
                     "error": "device/oracle verdict mismatch"}
                )
            )
            return

        from tpunode.events import events as _events
        from tpunode.metrics import metrics
        from tpunode.trace import profile_to, span
        from tpunode.tracectx import start_trace, tracer
        from tpunode.verify.engine import VerifyEngine

        # Device-profile capture (ISSUE 16): TPUNODE_PROFILE keeps its
        # exact legacy meaning (capture into that directory); with
        # TPUNODE_PROFILE_DIR set instead, each run captures into its own
        # labeled subdirectory and the path rides along in the JSON so
        # the watcher can bank profiles alongside verdicts.
        prof_dir = os.environ.get("TPUNODE_PROFILE")
        profile_path = None
        if not prof_dir:
            prof_base = os.environ.get("TPUNODE_PROFILE_DIR")
            if prof_base:
                profile_path = os.path.join(
                    prof_base,
                    f"bench-{kernel_name}-b{batch}-{int(time.time())}",
                )
                prof_dir = profile_path
        times = []
        with profile_to(prof_dir):
            for _ in range(iters):
                # each timed step is one causal trace: the slowest land in
                # the artifact's slowest_traces section, so a straggler
                # step is attributable (device vs readback) after the fact
                with start_trace("bench.step", batch=batch):
                    t0 = time.perf_counter()
                    # spanned like the engine's dispatch so the telemetry
                    # section reports the same distribution the node would
                    with span("verify.dispatch"):
                        device_fn(*args, **kw).block_until_ready()
                    times.append(time.perf_counter() - t0)
                metrics.observe(
                    "verify.occupancy",
                    1.0,  # the bench pads with real (tiled) items
                    buckets=VerifyEngine.OCCUPANCY_BUCKETS,
                )
        if profile_path is not None and not os.path.isdir(profile_path):
            profile_path = None  # profiler unavailable: nothing captured
        dt = statistics.median(times)
        print(
            json.dumps(
                {
                    "ok": True,
                    "rate": batch / dt,
                    "profile_path": profile_path,
                    "device": device_kind(),
                    "kernel": kernel_name,
                    "point_form": _point_form(),
                    "field_reduce": _field.reduce_mode(),
                    "window_bits": _kernel_mod.window_bits(),
                    "batch": batch,
                    "step_ms": round(dt * 1e3, 3),
                    "compile_s": round(compile_s, 1),
                    "init_s": round(init_s, 1),
                    "telemetry": metrics.telemetry(),
                    "slowest_traces": tracer.slowest(3),
                    "sanitizers": _sanitizer_counts(
                        _events.counts(), metrics
                    ),
                }
            )
        )
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_mempool() -> None:
    """Duplicate-heavy mempool-ingest scenario (ISSUE 5 satellite).

    A full Node with the mempool subsystem and the ORACLE verify backend
    (device-free: this worker must not depend on the tunnel) ingests
    heavily-overlapping tx sets from 4 in-process wire-speaking peers —
    one announcer serving ``getdata`` plus three firehose pushers all
    relaying the SAME unique set, with a few parent/child pairs pushed
    child-first to exercise orphan resolution.  Reports ingest
    efficiency: dedup hit-rate (the batch slots NOT wasted on
    re-verifying known txs), admission latency p50/p99 from the
    ``span.mempool.admit`` histogram, and orphan resolutions.  Prints
    one JSON line; the parent watchdog bounds it.
    """
    import asyncio

    n_txs = int(os.environ.get("TPUNODE_BENCH_MEMPOOL_TXS", 96))
    n_pairs = 4
    n_pushers = 3
    try:
        from benchmarks.txgen import gen_signed_txs
        from tests.fakenet import TxRelay, dummy_peer_connect
        from tests.fixtures import all_blocks
        from tpunode import BCH_REGTEST, Node, NodeConfig, Publisher, TxVerdict
        from tpunode.mempool import MempoolConfig
        from tpunode.metrics import metrics
        from tpunode.store import MemoryKV
        from tpunode.verify.engine import VerifyConfig

        net = BCH_REGTEST
        _progress(f"generating {n_txs} txs + {n_pairs} orphan pairs...")
        shared = gen_signed_txs(n_txs, inputs_per_tx=1, seed=0x3E3)
        pairs = [
            gen_signed_txs(2, inputs_per_tx=1, seed=0x0A20 + i,
                           segwit_every=2)
            for i in range(n_pairs)
        ]
        # child before parent: each pair parks then resolves
        orphan_feed = [t for funding, spender in pairs
                       for t in (spender, funding)]
        unique = {t.txid for t in shared} | {t.txid for t in orphan_feed}
        blocks = all_blocks()
        relays = {
            # one announcer: inv -> want-list -> getdata -> serve
            18801: TxRelay(shared, announce=True, mode="serve"),
            # orphan pusher: children first, then their parents
            18805: TxRelay(announce=False, push=orphan_feed),
        }
        for i in range(n_pushers):  # full-overlap firehose pushers
            relays[18802 + i] = TxRelay(announce=False, push=shared)

        async def run() -> dict:
            pub = Publisher(name="bench-mempool", maxsize=None)
            cfg = NodeConfig(
                net=net,
                store=MemoryKV(),
                pub=pub,
                peers=[f"[::1]:{port}" for port in relays],
                discover=False,
                max_peers=len(relays),
                connect=lambda sa: dummy_peer_connect(
                    net, blocks, relay=relays.get(sa[1])
                ),
                verify=VerifyConfig(backend="oracle", max_wait=0.0),
                mempool=MempoolConfig(tick_interval=0.05),
            )
            before = {
                name: metrics.get(name)
                for name in (
                    "mempool.admitted", "mempool.dedup_hits",
                    "mempool.announcements", "mempool.fetched",
                    "mempool.orphan_resolved", "mempool.orphaned",
                )
            }
            verdicts: set = set()
            t0 = time.perf_counter()
            timed_out = False
            async with pub.subscription() as events:
                async with Node(cfg):
                    while unique - verdicts:
                        try:
                            ev = await asyncio.wait_for(
                                events.receive(), 30.0
                            )
                        except asyncio.TimeoutError:
                            timed_out = True
                            break
                        if isinstance(ev, TxVerdict):
                            verdicts.add(ev.txid)
                    dt = time.perf_counter() - t0
                    # the last verdict can land while duplicate pushes
                    # are still queued: drain to the known delivery
                    # floor (every pusher relays the full shared set),
                    # then to quiescence — the serve-mode announcer's
                    # txs re-arrive via the push path too, an extra the
                    # floor can't predict — so the dedup numbers are
                    # not racily undercounted
                    floor = n_pushers * len(shared) + len(orphan_feed)

                    def _deliveries() -> float:
                        return (
                            metrics.get("mempool.admitted")
                            - before["mempool.admitted"]
                            + metrics.get("mempool.dedup_hits")
                            - before["mempool.dedup_hits"]
                        )

                    drain_deadline = time.perf_counter() + 20.0
                    last = -1.0
                    while time.perf_counter() < drain_deadline:
                        cur = _deliveries()
                        if cur >= floor and cur == last:
                            break  # floor reached and no growth for 0.2s
                        last = cur
                        await asyncio.sleep(0.2)
                    d = {
                        name: metrics.get(name) - v0
                        for name, v0 in before.items()
                    }
            hist = metrics.histogram("span.mempool.admit")
            deliveries = d["mempool.admitted"] + d["mempool.dedup_hits"]
            out = {
                "ok": not timed_out,
                "unique_txs": len(unique),
                "verdicts": len(verdicts),
                "deliveries": int(deliveries),
                "dedup_hits": int(d["mempool.dedup_hits"]),
                "dedup_hit_rate": round(
                    d["mempool.dedup_hits"] / deliveries, 4
                ) if deliveries else 0.0,
                "announcements": int(d["mempool.announcements"]),
                "fetched": int(d["mempool.fetched"]),
                "orphans_parked": int(d["mempool.orphaned"]),
                "orphan_resolutions": int(d["mempool.orphan_resolved"]),
                "admission_p50_ms": round(hist.quantile(0.5) * 1e3, 3)
                if hist is not None and hist.count else None,
                "admission_p99_ms": round(hist.quantile(0.99) * 1e3, 3)
                if hist is not None and hist.count else None,
                "wall_s": round(dt, 2),
                "txs_per_s": round(len(verdicts) / dt, 1) if dt else 0.0,
            }
            if timed_out:
                out["error"] = (
                    f"timed out with {len(unique - verdicts)} verdicts "
                    "outstanding"
                )
            return out

        _progress("running mempool fan-in scenario...")
        print(json.dumps(asyncio.run(run())))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_chaos() -> None:
    """Chaos resilience scenario (ISSUE 7): a full Node + mempool under a
    seeded fault plan — peer garbage on one pusher, random session drops,
    mempool-mailbox delivery delay, and a mid-run device loss — with the
    device SIMULATED (instant warmup + host-computed verdicts on the
    genuine tpu dispatch rung), so the breaker/ladder machinery is
    exercised without the tunnel.  Reports verdict conservation (every
    unique tx exactly one verdict, none with an error, no stuck
    PENDING), failover and breaker-transition counts, recovery latency
    p50/p99, and the sanitizer signals.  Prints one JSON line; the
    parent watchdog bounds it."""
    import asyncio

    n_txs = int(os.environ.get("TPUNODE_BENCH_CHAOS_TXS", 48))
    seed = int(os.environ.get("TPUNODE_BENCH_CHAOS_SEED", 1337))
    try:
        from benchmarks.txgen import gen_signed_txs
        from tests.fakenet import TxRelay, dummy_peer_connect
        from tests.fixtures import all_blocks
        from tpunode import BCH_REGTEST, Node, NodeConfig, Publisher, TxVerdict
        from tpunode.actors import task_registry
        from tpunode.chaos import ChaosPlan, chaos
        from tpunode.events import events as _events
        from tpunode.mempool import MempoolConfig
        from tpunode.metrics import metrics
        from tpunode.store import MemoryKV
        from tpunode.verify.engine import VerifyConfig, VerifyEngine

        # Simulated device: the engine's real tpu rung runs, verdicts are
        # computed on the host — breaker engaged, verify.tpu_items counted.
        import tpunode.verify.kernel as K
        from tpunode.verify.ecdsa_cpu import verify_batch_cpu

        VerifyEngine._warmup_fn = staticmethod(
            lambda bs, db=0: "tpu:chaos-sim"
        )
        K.dispatch_batch_tpu_raw = lambda chunk, pad_to=None: (
            verify_batch_cpu(chunk.to_tuples()), len(chunk),
        )
        K.collect_verdicts = lambda arr, count: arr

        plan_spec = os.environ.get("TPUNODE_CHAOS") or (
            f"seed={seed};"
            "peer.recv:garbage:p=0.05,n=2,match=18903;"
            "peer.recv:drop:p=0.02,n=3;"
            "mailbox.send:delay:p=0.05,dur=0.005,match=mempool;"
            "engine.dispatch:device_loss:match=tpu,after=1,n=3"
        )
        chaos.install(ChaosPlan.parse(plan_spec))
        net = BCH_REGTEST
        _progress(f"generating {n_txs} txs for the chaos scenario...")
        txs = gen_signed_txs(n_txs, inputs_per_tx=1, seed=0xC7A05)
        unique = {t.txid for t in txs}
        blocks = all_blocks()
        relays = {
            18901: TxRelay(txs, announce=True, mode="serve"),
            18902: TxRelay(txs, announce=True, mode="serve"),
            18903: TxRelay(announce=False, push=txs),  # the garbage target
        }

        def probe_items(count: int):
            """Tiny known-answer batch for driving the breaker recovery."""
            from tpunode.verify.ecdsa_cpu import (
                CURVE_N, GENERATOR, point_mul, sign,
            )

            items, expected = [], []
            for i in range(count):
                priv = (0xBEEF + i) % CURVE_N or 1
                pub_pt = point_mul(priv, GENERATOR)
                z = (0xF00D << i) % CURVE_N
                r, s = sign(priv, z, 0xC0FFEE + i)
                if i % 2:
                    z ^= 1
                items.append((pub_pt, z, r, s))
                expected.append(i % 2 == 0)
            return items, expected

        async def run() -> dict:
            pub = Publisher(name="bench-chaos", maxsize=None)
            cfg = NodeConfig(
                net=net,
                store=MemoryKV(),
                pub=pub,
                peers=[f"[::1]:{port}" for port in relays],
                discover=False,
                max_peers=len(relays),
                connect=lambda sa: dummy_peer_connect(
                    net, blocks, relay=relays.get(sa[1])
                ),
                verify=VerifyConfig(
                    backend="auto", max_wait=0.005, batch_size=64,
                    min_tpu_batch=1, breaker_threshold=2,
                    breaker_cooldown=0.2,
                ),
                mempool=MempoolConfig(tick_interval=0.05),
            )
            failovers0 = metrics.get("verify.failovers")
            stalls0 = _events.counts().get("watchdog.stall", 0)
            verdict_counts: dict = {}
            errors = 0
            t0 = time.perf_counter()
            timed_out = False
            async with pub.subscription() as sub:
                async with Node(cfg) as node:
                    eng = node.verify_engine
                    deadline = time.monotonic() + 60.0
                    while (
                        unique - set(verdict_counts)
                        and time.monotonic() < deadline
                    ):
                        try:
                            ev = await asyncio.wait_for(sub.receive(), 5.0)
                        except asyncio.TimeoutError:
                            continue
                        if isinstance(ev, TxVerdict):
                            verdict_counts[ev.txid] = (
                                verdict_counts.get(ev.txid, 0) + 1
                            )
                            if ev.error is not None:
                                errors += 1
                    if unique - set(verdict_counts):
                        timed_out = True
                    # drive the remaining injected device losses + the
                    # half-open canary recovery with direct batches
                    items, expected = probe_items(4)
                    drive_deadline = time.monotonic() + 30.0
                    conserved_probe = True
                    while time.monotonic() < drive_deadline:
                        got = await eng.verify(items)
                        if got != expected:
                            conserved_probe = False
                            break
                        if (
                            eng.breaker.opens >= 1
                            and eng.breaker.state == "ready"
                        ):
                            break
                        await asyncio.sleep(0.02)
                    tpu0 = metrics.get("verify.tpu_items")
                    await eng.verify(items)
                    device_restored = (
                        eng.breaker.state == "ready"
                        and metrics.get("verify.tpu_items") > tpu0
                    )
                    # stuck PENDING sweep (mempool processes our observed
                    # verdicts asynchronously: poll briefly)
                    stuck = 0
                    sweep_deadline = time.monotonic() + 10.0
                    while time.monotonic() < sweep_deadline:
                        stuck = sum(
                            1
                            for t in unique
                            if node.mempool.state(t) == "pending"
                        )
                        if not stuck:
                            break
                        await asyncio.sleep(0.1)
                    breaker = dict(eng.breaker.stats())
                    wall = time.perf_counter() - t0
            leaks = task_registry.report_leaks()
            dupes = sum(1 for v in verdict_counts.values() if v != 1)
            rec = metrics.histogram("verify.breaker_recovery_seconds")
            conserved = (
                not timed_out
                and dupes == 0
                and errors == 0
                and stuck == 0
                and conserved_probe
            )
            out = {
                "ok": conserved and device_restored,
                "plan": plan_spec,
                "unique_txs": len(unique),
                "verdicts": sum(verdict_counts.values()),
                "duplicate_verdicts": dupes,
                "error_verdicts": errors,
                "stuck_pending": stuck,
                "verdict_conservation": conserved,
                "failovers": int(
                    metrics.get("verify.failovers") - failovers0
                ),
                "breaker_opens": breaker["opens"],
                "breaker_closes": breaker["closes"],
                "breaker_state": breaker["state"],
                "device_path_restored": device_restored,
                "recovery_p50_ms": round(rec.quantile(0.5) * 1e3, 3)
                if rec is not None and rec.count else None,
                "recovery_p99_ms": round(rec.quantile(0.99) * 1e3, 3)
                if rec is not None and rec.count else None,
                "injections": {
                    f["fault"]: f["fired"]
                    for f in chaos.stats()["faults"]
                },
                "task_leaks": len(leaks),
                "watchdog_stalls": int(
                    _events.counts().get("watchdog.stall", 0) - stalls0
                ),
                "wall_s": round(wall, 2),
            }
            if timed_out:
                out["error"] = (
                    f"timed out with "
                    f"{len(unique - set(verdict_counts))} verdicts "
                    "outstanding"
                )
            return out

        _progress("running chaos resilience scenario...")
        print(json.dumps(asyncio.run(run())))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_recovery() -> None:
    """Crash-recovery scenario worker (ISSUE 9): makes recovery cost a
    tracked number.  Measures (1) reopen/replay latency at two log sizes
    (records/s and MB/s of the streamed v2 replay), (2) the compaction
    pause on the larger store, and (3) a bounded kill-torture sweep —
    real writer children killed at seeded append/rotate/compact points +
    bit-flip detection runs, reporting the pass rate.  jax is never
    imported; the parent watchdog bounds the whole worker."""
    import shutil
    import tempfile

    torture_budget = float(
        os.environ.get("TPUNODE_BENCH_RECOVERY_TORTURE_S", 75)
    )
    try:
        from tpunode.store import LogKV, put_op
        from tpunode.torture import sweep

        out: dict = {"ok": True, "replay": []}
        base = tempfile.mkdtemp(prefix="tpunode-recovery-")
        try:
            # 1) reopen/replay latency vs log size
            for label, n_records in (("small", 2_000), ("large", 20_000)):
                _progress(f"building {label} log ({n_records} records)...")
                path = os.path.join(base, f"replay-{label}", "kv.log")
                s = LogKV(path)
                batch = [
                    put_op(b"k%08d" % i, (b"v%08d" % i) * 12)
                    for i in range(n_records)
                ]
                for i in range(0, n_records, 500):
                    s.write_batch(batch[i : i + 500])
                s.close()
                size = sum(
                    os.path.getsize(os.path.join(d, f))
                    for d, _, fs in os.walk(os.path.dirname(path))
                    for f in fs
                )
                t0 = time.perf_counter()
                s2 = LogKV(path)
                open_s = time.perf_counter() - t0
                row = {
                    "label": label,
                    "records": n_records,
                    "bytes": size,
                    "open_ms": round(open_s * 1e3, 1),
                    "records_per_s": round(n_records / open_s),
                    "mb_per_s": round(size / open_s / 1e6, 1),
                }
                # 2) compaction pause on the large store (overwrites first
                # so compaction has real garbage to drop)
                if label == "large":
                    for i in range(0, 5_000, 500):
                        s2.write_batch(
                            [put_op(b"k%08d" % j, b"fresh" * 16)
                             for j in range(i, i + 500)]
                        )
                    t0 = time.perf_counter()
                    s2.compact()
                    out["compaction_pause_ms"] = round(
                        (time.perf_counter() - t0) * 1e3, 1
                    )
                s2.close()
                out["replay"].append(row)
            # 3) bounded kill-torture sweep (real subprocess children)
            _progress("running kill-torture sweep...")
            res = sweep(
                os.path.join(base, "torture"), seeds=(1,), ops=24,
                seg_bytes=1000, compact_every=10, bit_flips=2,
                budget_s=torture_budget,
            )
            out["torture"] = {
                "kill_points": res.points,
                "completed_runs": res.completed,
                "corruption_detected": res.corruption_detected,
                "violations": res.violations[:10],
                "pass": res.ok,
            }
            if not res.ok:
                out["ok"] = False
                out["error"] = (
                    f"{len(res.violations)} torture invariant violation(s)"
                )
        finally:
            shutil.rmtree(base, ignore_errors=True)
        print(json.dumps(out))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_pipeline() -> None:
    """Streaming-pipeline A/B (ISSUE 10): e2e ingest throughput of the
    duplicate-heavy mempool firehose through a full Node on the cpu
    proxy, SERIAL (``pipeline_depth=1, extract_workers=1`` — the
    pre-pipeline dispatch) vs PIPELINED (depth 2, pooled extraction).

    The workload is signature-bound by construction (2-input signed txs,
    every tx pushed twice so the mempool's dedup admission sees the
    duplicate-heavy shape): the A/B isolates what the lane packer +
    overlapped dispatch + parallel extraction buy on identical traffic.
    Reports e2e sigs/s both ways, the speedup, mean lane occupancy
    (pack efficiency) under saturation, host stage busy fractions
    (extract/dispatch/commit span time over wall), and an
    extraction-only worker scaling curve 1→4.  Prints one JSON line;
    the parent watchdog bounds it.
    """
    import asyncio

    n_txs = int(os.environ.get("TPUNODE_BENCH_PIPELINE_TXS", 2500))
    try:
        from benchmarks.txgen import gen_signed_txs
        from tpunode import BCH_REGTEST, Node, NodeConfig, Publisher, TxVerdict
        from tpunode.mempool import MempoolConfig
        from tpunode.metrics import metrics
        from tpunode.peer import PeerMessage
        from tpunode.store import MemoryKV
        from tpunode.verify.engine import VerifyConfig
        from tpunode.wire import LazyTx, MsgTx

        import tpunode.node as node_mod

        if not node_mod._native_extract_available():
            print(json.dumps(
                {"ok": False, "error": "native extractor unavailable"}
            ))
            return
        net = BCH_REGTEST
        _progress(f"generating {n_txs} signed txs (2 inputs each)...")
        signed = gen_signed_txs(n_txs, inputs_per_tx=2, seed=0x919E)
        # wire form (LazyTx with raw bytes, exactly what MsgTx decodes
        # to): the accumulator/native-extract fast path requires raw
        txs = [LazyTx(t.serialize()) for t in signed]
        n_sigs = sum(len(t.inputs) for t in signed)
        unique = {t.txid for t in signed}

        class _Pusher:  # minimal peer surface for the router/mempool
            def __init__(self, label):
                self.label = label

            def kill(self, exc):  # pragma: no cover - healthy traffic
                pass

        async def run_once(depth: int, workers: int) -> dict:
            metrics.reset()
            pub = Publisher(name="bench-pipeline", maxsize=None)
            cfg = NodeConfig(
                net=net,
                store=MemoryKV(),
                pub=pub,
                peers=[],  # traffic is injected directly on the router
                discover=False,
                verify=VerifyConfig(
                    backend="cpu", max_wait=0.005, batch_size=256,
                    pipeline_depth=depth,
                ),
                mempool=MempoolConfig(tick_interval=0.05),
                extract_workers=workers,
            )
            p1, p2 = _Pusher("fire:1"), _Pusher("fire:2")
            verdicts: set = set()
            timed_out = False
            async with pub.subscription() as events:
                async with Node(cfg) as node:
                    t0 = time.perf_counter()
                    for t in txs:  # firehose + full duplicate push
                        node._peer_pub.publish(
                            PeerMessage(p1, MsgTx(t))
                        )
                        node._peer_pub.publish(
                            PeerMessage(p2, MsgTx(t))
                        )
                    while unique - verdicts:
                        try:
                            ev = await asyncio.wait_for(
                                events.receive(), 30.0
                            )
                        except asyncio.TimeoutError:
                            timed_out = True
                            break
                        if isinstance(ev, TxVerdict):
                            verdicts.add(ev.txid)
                    dt = time.perf_counter() - t0
            out = {
                "pipeline_depth": depth,
                "extract_workers": workers,
                "verdicts": len(verdicts),
                "wall_s": round(dt, 3),
                "sigs_per_s": round(n_sigs / dt, 1) if dt else 0.0,
                "dedup_hits": int(metrics.get("mempool.dedup_hits")),
            }
            pack = metrics.histogram("sched.pack_efficiency")
            if pack is not None and pack.count:
                out["lanes"] = pack.count
                out["pack_efficiency_mean"] = round(pack.mean, 4)
                out["lane_occupancy_p50"] = round(
                    pack.quantile(0.5) or 0.0, 4
                )
            busy = {}
            for stage, name in (
                ("extract", "span.node.extract"),
                ("dispatch", "span.verify.dispatch"),
                ("commit", "span.node.commit"),
            ):
                h = metrics.histogram(name)
                if h is not None and h.count and dt:
                    busy[stage] = round(h.total / dt, 4)
            out["stage_busy"] = busy
            if timed_out:
                out["error"] = (
                    f"timed out with {len(unique - verdicts)} verdicts "
                    "outstanding"
                )
            return out

        def extract_scaling() -> dict:
            """Extraction-only scaling curve: one shard per worker over
            the same tx region, pure native extract (no engine)."""
            from concurrent.futures import ThreadPoolExecutor

            from tpunode.txextract import ParsedTxRegion

            raws = [t.serialize() for t in txs]
            curve: dict = {}
            for w in (1, 2, 4):
                shard_sz = (len(raws) + w - 1) // w
                shards = [
                    (b"".join(raws[i : i + shard_sz]),
                     len(raws[i : i + shard_sz]))
                    for i in range(0, len(raws), shard_sz)
                ]

                def one(shard):
                    data, n = shard
                    with ParsedTxRegion(data, n) as region:
                        return region.extract(intra_amounts=False).count

                best = None
                with ThreadPoolExecutor(max_workers=w) as pool:
                    for _ in range(3):
                        t0 = time.perf_counter()
                        total = sum(pool.map(one, shards))
                        dt = time.perf_counter() - t0
                        assert total > 0
                        best = dt if best is None else min(best, dt)
                curve[str(w)] = round(len(raws) / best, 1)
            return curve

        async def run() -> dict:
            import os as _os

            workers = min(4, _os.cpu_count() or 1)
            _progress("serial baseline (depth 1, 1 extract worker)...")
            serial = await run_once(1, 1)
            _progress(f"pipelined (depth 2, {workers} extract workers)...")
            pipelined = await run_once(2, workers)
            out = {
                "ok": (
                    "error" not in serial and "error" not in pipelined
                ),
                "proxy": "cpu-native",
                "unique_txs": len(unique),
                "sigs": n_sigs,
                "serial": serial,
                "pipelined": pipelined,
            }
            if serial.get("sigs_per_s") and pipelined.get("sigs_per_s"):
                out["speedup"] = round(
                    pipelined["sigs_per_s"] / serial["sigs_per_s"], 3
                )
            _progress("extract-worker scaling curve...")
            out["extract_scaling_txs_per_s"] = extract_scaling()
            for side in ("serial", "pipelined"):
                if "error" in out[side]:
                    out["error"] = f"{side}: {out[side]['error']}"
                    break
            return out

        print(json.dumps(asyncio.run(run())))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_mesh() -> None:
    """Pod-scale fleet-dispatcher scaling (ISSUE 13): e2e verify
    throughput of the cross-host work-stealing fleet at 1/2/4/8-way
    dispatch on the cpu-native proxy.

    Each fleet host runs one dispatch worker whose cpu rung drives the
    C++ engine with ONE OS thread (GIL-released), so k hosts = k cores
    of native verification and the scaling curve prices the DISPATCHER
    itself — lane packing, assignment, stealing, per-host bookkeeping —
    not the device.  1-way is the plain single-host pipeline
    (``mesh_hosts=0, pipeline_depth=1``), the honest serial baseline.
    Acceptance floor (ISSUE 13): >= 0.8x ideal at 4-way.  The worker
    also drives the adversarial campaign pool through the 4-way fleet
    and cross-checks verdict bit-identity against the single-chip path
    (a scheduler that drops, duplicates, or reorders slices would show
    up here, not just in throughput).  Prints one JSON line; the parent
    watchdog bounds it.
    """
    import asyncio

    sigs = int(os.environ.get("TPUNODE_BENCH_MESH_SIGS", 24576))
    ways_env = os.environ.get("TPUNODE_BENCH_MESH_WAYS_LIST", "1,2,4,8")
    try:
        from benchmarks.campaign import build_pool
        from benchmarks.common import make_triples, tile
        from tpunode.metrics import metrics
        from tpunode.verify.cpu_native import load_native_verifier
        from tpunode.verify.engine import VerifyConfig, VerifyEngine
        from tpunode.verify.raw import pack_items

        if load_native_verifier() is None:
            print(json.dumps(
                {"ok": False, "error": "native verifier unavailable"}
            ))
            return
        ways_list = [int(w) for w in ways_env.split(",") if w.strip()]
        _progress(f"generating {sigs} tiled sigs...")
        uniq = make_triples(min(2048, sigs))
        items = tile(uniq, sigs)
        raw = pack_items(items)
        # Submission grain chosen NOT to divide the 1024-item lane
        # target, so slices genuinely straddle lane boundaries and the
        # curve prices the packer's cross-submission bookkeeping too
        # (review r13: 512 packed two whole submissions per lane).
        sub = 500

        async def run_way(hosts: int) -> dict:
            metrics.reset()
            cfg = VerifyConfig(
                backend="cpu", batch_size=1024, max_wait=0.005,
                pipeline_depth=1, cpu_threads=1, warmup=False,
                mesh_hosts=hosts if hosts >= 2 else 0,
            )
            async with VerifyEngine(cfg) as eng:
                t0 = time.perf_counter()
                futs = [
                    # gathered three lines down; a supervisor would just
                    # add registry churn to the timed window
                    asyncio.ensure_future(  # asyncsan: disable=raw-spawn
                        eng.verify_raw(raw.slice(off, off + sub))
                    )
                    for off in range(0, len(raw), sub)
                ]
                got = await asyncio.gather(*futs)
                dt = time.perf_counter() - t0
                st = eng.stats()
            n = sum(len(g) for g in got)
            assert n == sigs
            out = {
                "hosts": hosts,
                "wall_s": round(dt, 3),
                "sigs_per_s": round(sigs / dt, 1) if dt else 0.0,
            }
            fleet = st.get("fleet")
            if fleet:
                out["steals"] = fleet["steals"]
                out["requeued"] = fleet["requeued"]
            return out

        async def campaign_parity() -> dict:
            import random as _random

            items_c, shapes, expects = build_pool(
                24, _random.Random(0x13E5)
            )
            async def through(hosts: int) -> list:
                cfg = VerifyConfig(
                    backend="cpu", batch_size=64, max_wait=0.005,
                    pipeline_depth=1, warmup=False,
                    mesh_hosts=hosts if hosts >= 2 else 0,
                )
                async with VerifyEngine(cfg) as eng:
                    futs, k, i = [], 0, 0
                    sizes = [37, 53, 11, 97, 5]
                    while k < len(items_c):
                        n = sizes[i % len(sizes)]
                        i += 1
                        # awaited in the return below (whole-list drain)
                        futs.append(asyncio.ensure_future(  # asyncsan: disable=raw-spawn
                            eng.verify(items_c[k : k + n])
                        ))
                        k += n
                    return [v for f in futs for v in await f]

            fleet_v = await through(4)
            single_v = await through(0)
            mism = [
                (j, shapes[j])
                for j, (g, e) in enumerate(zip(fleet_v, expects))
                if g != e
            ]
            return {
                "items": len(items_c),
                "mismatches": len(mism),
                "single_chip_identical": fleet_v == single_v,
                "clean": not mism and fleet_v == single_v,
                **({"first_mismatches": mism[:5]} if mism else {}),
            }

        async def run() -> dict:
            ways: dict = {}
            for k in ways_list:
                _progress(f"{k}-way fleet...")
                ways[str(k)] = await run_way(k)
            # speedup/efficiency AFTER every way ran (review r13: a
            # baseline-last or baseline-free TPUNODE_BENCH_MESH_WAYS_LIST
            # must not silently skip the acceptance gate)
            base_rate = ways.get("1", {}).get("sigs_per_s")
            for k_str, cell in ways.items():
                k = int(k_str)
                if k != 1 and base_rate:
                    cell["speedup"] = round(
                        cell["sigs_per_s"] / base_rate, 3
                    )
                    cell["efficiency"] = round(
                        cell["sigs_per_s"] / (k * base_rate), 3
                    )
            _progress("campaign parity through the 4-way fleet...")
            camp = await campaign_parity()
            eff4 = ways.get("4", {}).get("efficiency")
            out = {
                "ok": bool(camp["clean"]) and (
                    eff4 is None or eff4 >= 0.8
                ),
                "proxy": "cpu-native",
                "sigs": sigs,
                "unique": len(uniq),
                "submission_items": sub,
                "ways": ways,
                "scaling_floor": 0.8,
                "scaling_at_4": eff4,
                "campaign": camp,
            }
            if not camp["clean"]:
                out["fatal"] = True  # verdict divergence, never mask
                out["error"] = "fleet/single-chip verdict mismatch"
            elif eff4 is not None and eff4 < 0.8:
                out["error"] = (
                    f"4-way scaling {eff4} below the 0.8x-ideal floor"
                )
            elif "4" in ways and eff4 is None:
                # 4-way ran but no 1-way baseline: the floor cannot be
                # evaluated — label it, never report a silent pass
                out["ok"] = False
                out["error"] = (
                    "4-way ran without a 1-way baseline — the 0.8x "
                    "scaling floor was not evaluated"
                )
            return out

        print(json.dumps(asyncio.run(run())))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_mesh_e2e() -> None:
    """Host-affine feed A/B (ISSUE 19): the ingest→extract/pack→dispatch
    →verdict path at 4-way on the cpu-native proxy, affinity ON (keyed
    submissions land in their home host's packer; intake gates on the
    TARGET host's feed depth) vs the central-feed baseline (keyless
    submissions through the shared packer; intake gates on GLOBAL
    unresolved pending — the pre-affinity node policy).

    Both legs get the identical workload (keyed ingest batches, each
    packed in-loop — the extract/pack stage is inside the timed window),
    the identical deferred-intake retry tick, and the identical fault:
    host h0's dispatch stalls ``slow_s`` per lane.  The gates differ the
    way the policies differ: the baseline's global budget is ONE
    pipeline's feed ceiling — fleet-blind, like the node's fixed
    ``MAX_VERIFY_PENDING`` was before affinity — while the affine leg
    budgets the SAME ceiling per host (per-host gates scale intake with
    the fleet by construction).  That asymmetry is the policy under
    test: a per-host gate defers ONLY the slow host's keys while the
    rest of the fleet stays fed; the global gate parks the whole intake
    stream behind the retry timer whenever total unresolved work — most
    of it stuck behind the slow host — trips the one shared budget.  The
    retry tick is 0.25s, deliberately kinder to the baseline than the
    node's real deferral granularity (the 1s mempool scheduler tick).
    Per-host ``feed_idle`` (idle-take fraction) is reported for both legs
    as the starvation signal.  The campaign pool additionally runs
    through the affine path and is cross-checked bit-identical against
    the single-chip verdicts.  Prints one JSON line; the parent watchdog
    bounds it.
    """
    import asyncio
    import hashlib

    sigs = int(os.environ.get("TPUNODE_BENCH_MESH_E2E_SIGS", 12288))
    hosts = int(os.environ.get("TPUNODE_BENCH_MESH_E2E_HOSTS", 4))
    try:
        from benchmarks.campaign import build_pool
        from benchmarks.common import make_triples, tile
        from tpunode.metrics import metrics
        from tpunode.verify.cpu_native import load_native_verifier
        from tpunode.verify.engine import VerifyConfig, VerifyEngine
        from tpunode.verify.raw import pack_items
        from tpunode.verify.sched import affinity_key

        if load_native_verifier() is None:
            print(json.dumps(
                {"ok": False, "error": "native verifier unavailable"}
            ))
            return
        batch_items = 256  # one ingest batch = one getdata-sized unit
        lane = 256         # small lane target -> tight per-host ceiling
        retry_s = 0.25     # deferred-intake retry tick (see docstring)
        slow_s = 0.05      # injected h0 stall per dispatched lane
        _progress(f"generating {sigs} tiled sigs...")
        uniq = make_triples(min(2048, sigs))
        items = tile(uniq, sigs)
        batches = [
            items[off : off + batch_items]
            for off in range(0, len(items), batch_items)
        ]
        # one stable pseudo-txid per ingest batch: the affinity key is a
        # pure function of the batch index, so both legs and every rerun
        # route identically
        keys = [
            affinity_key(
                hashlib.blake2b(b"mesh-e2e-%d" % i, digest_size=8).digest()
            )
            for i in range(len(batches))
        ]

        def _slow_h0(eng) -> None:
            # the same dispatch seam the scheduler tests use: h0 sleeps
            # in its dispatch worker thread, so its queue backs up while
            # the loop (and the other hosts) keep running
            orig = eng._dispatch_multi

            def wrapper(payloads, target=None, host=None, backend=None):
                if host is not None and host.name == "h0":
                    time.sleep(slow_s)
                if host is None and backend is None:
                    return orig(payloads, target)
                return orig(payloads, target, host=host, backend=backend)

            eng._dispatch_multi = wrapper

        async def run_leg(affine: bool) -> dict:
            metrics.reset()
            cfg = VerifyConfig(
                backend="cpu", batch_size=lane, max_wait=0.005,
                pipeline_depth=1, cpu_threads=1, warmup=False,
                mesh_hosts=hosts,
            )
            async with VerifyEngine(cfg) as eng:
                _slow_h0(eng)
                # the baseline's budget: ONE pipeline's feed ceiling,
                # fleet-blind (pre-affinity MAX_VERIFY_PENDING shape);
                # the affine leg's per-host gates carry the same
                # ceiling PER HOST inside eng.host_pressured()
                limit_global = eng._feed_limit()
                pending = 0
                deferrals = 0
                futs = []

                def _dec(_f, n: int) -> None:
                    nonlocal pending
                    pending -= n

                t0 = time.perf_counter()
                for b, key in zip(batches, keys):
                    if affine:
                        while eng.host_pressured(key):
                            deferrals += 1
                            await asyncio.sleep(retry_s)
                    else:
                        while pending >= limit_global:
                            deferrals += 1
                            await asyncio.sleep(retry_s)
                    raw = pack_items(b)  # extract/pack inside the window
                    pending += len(b)
                    fut = asyncio.ensure_future(  # asyncsan: disable=raw-spawn
                        eng.verify_raw(
                            raw, priority="mempool",
                            affinity=key if affine else None,
                        )
                    )
                    fut.add_done_callback(
                        lambda f, n=len(b): _dec(f, n)
                    )
                    futs.append(fut)
                got = await asyncio.gather(*futs)
                dt = time.perf_counter() - t0
                st = eng.stats()
            n = sum(len(g) for g in got)
            assert n == sigs
            fleet = st["fleet"]
            out = {
                "affine": affine,
                "wall_s": round(dt, 3),
                "sigs_per_s": round(sigs / dt, 1) if dt else 0.0,
                "deferrals": deferrals,
                "feed_idle": fleet["feed_idle"],
                "steals": fleet["steals"],
            }
            if affine:
                out["affinity"] = fleet["affinity"]
            return out

        async def campaign_affine() -> dict:
            # the adversarial pool through the AFFINE path: every chunk
            # keyed, verdicts bit-identical to the single-chip pass (a
            # router that dropped, duplicated, or cross-wired a keyed
            # submission would show up here, not just in throughput)
            import random as _random

            items_c, shapes, expects = build_pool(
                24, _random.Random(0x13E5)
            )

            async def through(fleet_hosts: int) -> list:
                cfg = VerifyConfig(
                    backend="cpu", batch_size=64, max_wait=0.005,
                    pipeline_depth=1, warmup=False,
                    mesh_hosts=fleet_hosts,
                )
                async with VerifyEngine(cfg) as eng:
                    futs, k, i = [], 0, 0
                    sizes = [37, 53, 11, 97, 5]
                    while k < len(items_c):
                        n = sizes[i % len(sizes)]
                        aff = (
                            affinity_key(hashlib.blake2b(
                                b"camp-%d" % i, digest_size=8
                            ).digest())
                            if fleet_hosts else None
                        )
                        i += 1
                        # awaited in the return below (whole-list drain)
                        futs.append(asyncio.ensure_future(  # asyncsan: disable=raw-spawn
                            eng.verify(
                                items_c[k : k + n], affinity=aff
                            )
                        ))
                        k += n
                    return [v for f in futs for v in await f]

            affine_v = await through(hosts)
            single_v = await through(0)
            mism = [
                (j, shapes[j])
                for j, (g, e) in enumerate(zip(affine_v, expects))
                if g != e
            ]
            return {
                "items": len(items_c),
                "mismatches": len(mism),
                "single_chip_identical": affine_v == single_v,
                "clean": not mism and affine_v == single_v,
                **({"first_mismatches": mism[:5]} if mism else {}),
            }

        async def run() -> dict:
            _progress("central-feed baseline leg...")
            central = await run_leg(affine=False)
            _progress("affine leg...")
            affine = await run_leg(affine=True)
            _progress("campaign through the affine path...")
            camp = await campaign_affine()
            ratio = (
                round(affine["sigs_per_s"] / central["sigs_per_s"], 3)
                if central["sigs_per_s"] else None
            )
            floor = 1.25
            out = {
                "ok": bool(camp["clean"])
                and ratio is not None and ratio >= floor,
                "proxy": "cpu-native",
                "sigs": sigs,
                "hosts": hosts,
                "batch_items": batch_items,
                "slow_host": {"host": "h0", "stall_s": slow_s},
                "retry_s": retry_s,
                "central": central,
                "affine": affine,
                "speedup": ratio,
                "speedup_floor": floor,
                "campaign": camp,
            }
            if not camp["clean"]:
                out["fatal"] = True  # verdict divergence, never mask
                out["error"] = "affine-path/single-chip verdict mismatch"
            elif ratio is None:
                out["error"] = "central baseline produced no rate"
            elif ratio < floor:
                out["error"] = (
                    f"affine/central speedup {ratio} below the "
                    f"{floor}x floor"
                )
            return out

        print(json.dumps(asyncio.run(run())))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_serve() -> None:
    """Multi-tenant serve firehose (ISSUE 20): >=1000 simulated clients
    over REAL sockets against a live ServeServer on the cpu-native
    proxy.  Zipf-distributed duplicates over a ~2048-unique signed-row
    pool (the shared verdict cache must absorb the repeats at zero
    verify cost), 8 tenants across all four priority classes.  Two
    legs: (1) the firehose — per-class verdict-latency p50/p99, cache
    hit-rate, and the CONSERVATION pin: the engine verifies each unique
    row exactly once (first submitter wins, duplicates coalesce/hit),
    and every verdict matches the pool's known validity pattern — any
    divergence is ``fatal`` exactly like a headline verdict mismatch;
    (2) the induced-burn leg — the server's SLO hook reports a
    fast-window burn, and ONLY bulk-class tenants may shed while
    block-class p99 stays inside the DEFAULT_SLOS block objective.  A
    receipt log rides the whole run in a tempdir and must audit clean
    (hash chain + CRC walk); its per-append overhead is reported.
    Prints one JSON line; the parent watchdog bounds it.
    """
    import asyncio
    import contextlib
    import itertools
    import random
    import tempfile

    clients_n = int(os.environ.get("TPUNODE_BENCH_SERVE_CLIENTS", 1000))
    frames_per = int(os.environ.get("TPUNODE_BENCH_SERVE_FRAMES", 3))
    items_per = int(os.environ.get("TPUNODE_BENCH_SERVE_ITEMS", 12))
    try:
        from benchmarks.common import make_triples
        from tpunode.metrics import metrics
        from tpunode.receipts import ReceiptLog, audit
        from tpunode.serve import ServeServer, TenantConfig
        from tpunode.slo import DEFAULT_SLOS
        from tpunode.verify.cpu_native import load_native_verifier
        from tpunode.verify.engine import VerifyConfig, VerifyEngine

        if load_native_verifier() is None:
            print(json.dumps(
                {"ok": False, "error": "native verifier unavailable"}
            ))
            return
        uniq_n = 2048
        invalid_every = 16
        _progress(f"generating {uniq_n} unique signed rows...")
        triples = make_triples(uniq_n, invalid_every=invalid_every)
        rows = [
            [
                z.to_bytes(32, "big").hex(),
                (
                    b"\x04"
                    + q.x.to_bytes(32, "big")
                    + q.y.to_bytes(32, "big")
                ).hex(),
                (r.to_bytes(32, "big") + s.to_bytes(32, "big")).hex(),
            ]
            for (q, z, r, s) in triples
        ]
        # make_triples corrupts every invalid_every-th message: the
        # expected verdict per row index is known a priori, so every
        # client checks every reply bit (the conservation tally's twin)
        expected = [
            i % invalid_every != invalid_every - 1 for i in range(uniq_n)
        ]
        # Zipf(1.1) over the pool: head rows repeat constantly (cache
        # fodder), the tail keeps fresh verify work arriving
        cum_w = list(itertools.accumulate(
            1.0 / (i + 1) ** 1.1 for i in range(uniq_n)
        ))
        classes = ("block", "mempool", "ibd", "bulk")
        tenants = [
            TenantConfig(
                name=f"t{i}", token=f"tok-{i}",
                priority=classes[i % len(classes)],
                rate=1e9, burst=1e9, max_inflight=8192,
            )
            for i in range(8)
        ]
        block_slo = next(
            s for s in DEFAULT_SLOS
            if s.kind == "latency" and s.priority == "block"
        )

        async def run() -> dict:
            metrics.reset()
            burn: dict = {"on": False}
            counted = {"verify_items": 0}
            tmp = tempfile.mkdtemp(prefix="tpunode-serve-bench-")
            cfg = VerifyConfig(
                backend="cpu", batch_size=256, max_wait=0.002,
                pipeline_depth=1, cpu_threads=1, warmup=False,
            )
            receipts = ReceiptLog(tmp)
            async with VerifyEngine(cfg) as eng:
                orig_verify = eng.verify

                async def counting_verify(items, **kw):
                    counted["verify_items"] += len(items)
                    return await orig_verify(items, **kw)

                eng.verify = counting_verify
                async with ServeServer(
                    eng, tenants, port=0,
                    slo_burning=lambda: (
                        ["verdict-latency-block"] if burn["on"] else []
                    ),
                    receipts=receipts,
                ) as srv:
                    lat: dict = {}
                    sem = asyncio.Semaphore(250)  # fd + loop sanity

                    async def one_client(
                        ci: int, leg: str, tally: dict
                    ) -> None:
                        t = tenants[ci % len(tenants)]
                        rng = random.Random(0x5E12C1 ^ (ci * 2654435761))
                        async with sem:
                            reader, writer = await asyncio.open_connection(
                                "127.0.0.1", srv.port
                            )
                            try:
                                for fi in range(frames_per):
                                    idxs = rng.choices(
                                        range(uniq_n), cum_weights=cum_w,
                                        k=items_per,
                                    )
                                    frame = {
                                        "tenant": t.name, "token": t.token,
                                        "items": [rows[j] for j in idxs],
                                        "id": fi,
                                    }
                                    data = json.dumps(
                                        frame, separators=(",", ":")
                                    ).encode()
                                    t0 = time.perf_counter()
                                    writer.write(
                                        len(data).to_bytes(4, "big") + data
                                    )
                                    await writer.drain()
                                    hdr = await reader.readexactly(4)
                                    body = await reader.readexactly(
                                        int.from_bytes(hdr, "big")
                                    )
                                    dt = time.perf_counter() - t0
                                    reply = json.loads(body)
                                    lat.setdefault(
                                        (leg, t.priority), []
                                    ).append(dt)
                                    if reply.get("ok"):
                                        vs = reply["verdicts"]
                                        tally["verdicts"] += len(vs)
                                        tally["cached"] += reply.get(
                                            "cached", 0
                                        )
                                        tally["seen"].update(idxs)
                                        tally["wrong"] += sum(
                                            1
                                            for j, v in zip(idxs, vs)
                                            if bool(v) != expected[j]
                                        )
                                    elif reply.get("error") == "shed":
                                        shed = tally["shed_by_class"]
                                        shed[t.priority] = (
                                            shed.get(t.priority, 0)
                                            + len(reply.get("verdicts") or ())
                                        )
                                    elif reply.get("error") == "throttled":
                                        tally["throttled"] += 1
                                    else:
                                        tally["errors"] += 1
                            finally:
                                with contextlib.suppress(Exception):
                                    writer.close()
                                    await writer.wait_closed()

                    def fresh_tally() -> dict:
                        return {
                            "verdicts": 0, "cached": 0, "wrong": 0,
                            "throttled": 0, "errors": 0,
                            "shed_by_class": {}, "seen": set(),
                        }

                    _progress(f"firehose leg: {clients_n} clients...")
                    fire = fresh_tally()
                    t0 = time.perf_counter()
                    await asyncio.gather(*(
                        one_client(ci, "fire", fire)
                        for ci in range(clients_n)
                    ))
                    fire_wall = time.perf_counter() - t0
                    verified_fire = counted["verify_items"]

                    burn_clients = max(256, len(tenants) * 16)
                    _progress(
                        f"induced-burn leg: {burn_clients} clients..."
                    )
                    burn["on"] = True
                    bleg = fresh_tally()
                    await asyncio.gather(*(
                        one_client(ci, "burn", bleg)
                        for ci in range(burn_clients)
                    ))
                    burn["on"] = False
                    srv_stats = srv.stats()
            receipts.close()
            verdict = audit(tmp)

            def pcts(key) -> dict:
                xs = sorted(lat.get(key, ()))
                if not xs:
                    return {"p50": None, "p99": None, "n": 0}
                return {
                    "p50": round(xs[len(xs) // 2], 4),
                    "p99": round(xs[min(len(xs) - 1, int(len(xs) * 0.99))], 4),
                    "n": len(xs),
                }

            # conservation: every unique row that reached admission was
            # verified EXACTLY once during the firehose; everything else
            # (the Zipf mass) came out of the shared cache
            conserve_ok = (
                verified_fire == len(fire["seen"])
                and fire["cached"] + verified_fire == fire["verdicts"]
            )
            wrong = fire["wrong"] + bleg["wrong"]
            shed_classes = sorted(bleg["shed_by_class"])
            burn_block_p99 = pcts(("burn", "block"))["p99"]
            shed_ok = (
                bool(bleg["shed_by_class"])
                and shed_classes == ["bulk"]
                and not fire["shed_by_class"]
            )
            p99_ok = (
                burn_block_p99 is not None
                and burn_block_p99 <= block_slo.threshold
            )
            appended = metrics.get("receipts.appended")
            out = {
                "ok": (
                    wrong == 0 and conserve_ok and shed_ok and p99_ok
                    and bool(verdict["ok"]) and fire["errors"] == 0
                    and bleg["errors"] == 0
                ),
                "proxy": "cpu-native",
                "clients": clients_n + burn_clients,
                "tenants": len(tenants),
                "unique_rows": uniq_n,
                "frames_per_client": frames_per,
                "items_per_frame": items_per,
                "firehose": {
                    "wall_s": round(fire_wall, 3),
                    "verdicts": fire["verdicts"],
                    "verified_unique": verified_fire,
                    "unique_submitted": len(fire["seen"]),
                    "cache_hits": fire["cached"],
                    "cache_hit_rate": round(
                        fire["cached"] / fire["verdicts"], 4
                    ) if fire["verdicts"] else None,
                    "throttled": fire["throttled"],
                    "wire_errors": fire["errors"],
                },
                "latency": {
                    cls: pcts(("fire", cls)) for cls in classes
                },
                "burn_leg": {
                    "shed_by_class": bleg["shed_by_class"],
                    "shed_classes": shed_classes,
                    "block_p99": burn_block_p99,
                    "block_objective_s": round(block_slo.threshold, 4),
                    "verdicts": bleg["verdicts"],
                    "wire_errors": bleg["errors"],
                },
                "conservation": {
                    "ok": conserve_ok,
                    "verified": verified_fire,
                    "unique_submitted": len(fire["seen"]),
                },
                "receipts": {
                    "records": verdict["records"],
                    "segments": verdict["segments"],
                    "audit_ok": bool(verdict["ok"]),
                    "findings": verdict["findings"][:5],
                    "append_ms_avg": round(
                        1e3 * metrics.get("receipts.append_seconds")
                        / appended, 4
                    ) if appended else None,
                },
                "spend_by_tenant": srv_stats.get("spend", {}).get(
                    "by_tenant", {}
                ),
            }
            if wrong:
                out["fatal"] = True  # verdict divergence, never mask
                out["error"] = (
                    f"{wrong} served verdicts diverged from the pool's "
                    "known validity pattern"
                )
            elif not conserve_ok:
                out["fatal"] = True
                out["error"] = (
                    "verdict conservation broke: "
                    f"verified {verified_fire} != unique "
                    f"{len(fire['seen'])} (or hits+verified != verdicts)"
                )
            elif not verdict["ok"]:
                out["error"] = "receipt audit found findings"
            elif not shed_ok:
                out["error"] = (
                    f"shed classes {shed_classes or 'none'} — expected "
                    "exactly ['bulk'] under burn and none before it"
                )
            elif not p99_ok:
                out["error"] = (
                    f"block-class p99 {burn_block_p99}s breached the "
                    f"{block_slo.threshold:.3f}s objective under burn"
                )
            return out

        print(json.dumps(asyncio.run(run())))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_mesh_device() -> None:
    """One device-mesh sharding sample (ISSUE 13; the watcher's
    ``kind="mesh"`` rungs): raw-batch dispatch through
    ``multichip.dispatch_raw_sharded`` at ``TPUNODE_BENCH_MESH_WAYS``-way
    sharding on the real device mesh (clamped to the visible device
    count), oracle cross-checked, timed at steady state.  Env contract
    mirrors ``--worker`` (TPUNODE_BENCH_REQUIRE_TPU, TPUNODE_BENCH_KERNEL,
    TPUNODE_BENCH_BATCH per-way)."""
    ways_req = int(os.environ.get("TPUNODE_BENCH_MESH_WAYS", 8))
    batch = int(os.environ.get("TPUNODE_BENCH_BATCH", 4096))
    require_tpu = os.environ.get("TPUNODE_BENCH_REQUIRE_TPU") == "1"
    iters = int(os.environ.get("TPUNODE_BENCH_ITERS", TIMED_ITERS))
    kernel = os.environ.get("TPUNODE_BENCH_KERNEL") or "auto"
    try:
        import jax

        from tpunode.verify.engine import enable_compile_cache

        enable_compile_cache()
        t0 = time.perf_counter()
        _progress("initializing backend (jax.devices may block)...")
        devs = jax.devices()
        init_s = time.perf_counter() - t0
        platform = getattr(devs[0], "platform", "?")
        if require_tpu and platform != "tpu":
            print(json.dumps(
                {"ok": False, "error": f"platform is {platform!r}, not tpu"}
            ))
            return
        ways = min(ways_req, len(devs))
        from benchmarks.common import device_kind, make_triples, tile
        from tpunode.verify.cpu_native import load_native_verifier
        from tpunode.verify.ecdsa_cpu import verify_batch_cpu
        from tpunode.verify.kernel import collect_verdicts
        from tpunode.verify.multichip import (
            dispatch_raw_sharded,
            make_hybrid_mesh,
        )
        from tpunode.verify.raw import pack_items

        mesh = make_hybrid_mesh(ways, 1)
        base = make_triples(min(UNIQUE, batch))
        raw = pack_items(tile(base, batch))
        _progress(f"compiling {ways}-way sharded program at batch {batch}...")
        t0 = time.perf_counter()
        got = collect_verdicts(
            *dispatch_raw_sharded(raw, mesh, pad_to=batch, kernel=kernel)
        )[: len(base)]
        compile_s = time.perf_counter() - t0
        native = load_native_verifier()
        expect = (
            native.verify_batch(base)
            if native is not None
            else verify_batch_cpu(base)
        )
        if got != expect:
            print(json.dumps(
                {"ok": False, "fatal": True,
                 "error": "mesh/oracle verdict mismatch"}
            ))
            return
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ok, _count = dispatch_raw_sharded(
                raw, mesh, pad_to=batch, kernel=kernel
            )
            ok.block_until_ready()
            times.append(time.perf_counter() - t0)
        dt = statistics.median(times)
        print(json.dumps({
            "ok": True,
            "rate": batch / dt,
            "device": device_kind(),
            "kernel": kernel,
            "mesh_ways": ways,
            "mesh_ways_requested": ways_req,
            "batch": batch,
            "step_ms": round(dt * 1e3, 3),
            "compile_s": round(compile_s, 1),
            "init_s": round(init_s, 1),
        }))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_ibd() -> None:
    """Long-IBD replay A/B over the persistent store (ISSUE 11): a bare
    Node syncs a fakenet chain through the REAL fetch planner
    (NodeConfig.ibd) — no embedder pushes anywhere — measured three ways:

    * ``ingest``: verify engine ON (cpu-native rung), native sharded
      extraction + C++ UTXO connect vs the serial all-Python baseline
      (python extract path, python block-connect) on identical traffic —
      e2e blocks/s and sigs/s with the speedup;
    * ``connect``: verify engine OFF — the pure block-ingest path (wire →
      parse → UTXO connect) native vs Python, the block-connect hot path
      in isolation;
    * ``kill9``: a child process killed mid-sync over a LogKV store, then
      restarted — proving the restart resumes from the watermark with
      ZERO re-verified (and zero re-fetched) blocks.

    Prints one JSON line; the parent watchdog bounds it.
    """
    import asyncio
    import shutil
    import signal
    import subprocess
    import tempfile

    # 129-tx blocks (incl. coinbase) put the BLOCK regions over the
    # 2*MIN_SHARD_TXS sharding threshold, so the native leg exercises the
    # per-tx-range worker-pool split the section exists to measure
    n_blocks = int(os.environ.get("TPUNODE_BENCH_IBD_BLOCKS", 240))
    txs_per_block = int(os.environ.get("TPUNODE_BENCH_IBD_TXS", 128))
    inputs_per_tx = int(os.environ.get("TPUNODE_BENCH_IBD_INPUTS", 1))
    kill_blocks = int(os.environ.get("TPUNODE_BENCH_IBD_KILL_BLOCKS", 1500))
    try:
        from benchmarks.txgen import gen_chain, synth_prevout
        from tpunode import (
            BCH_REGTEST,
            IbdConfig,
            Node,
            NodeConfig,
            Publisher,
            TxVerdict,
        )
        from tpunode.store import LogKV
        from tpunode.verify.engine import VerifyConfig

        import tpunode.node as node_mod

        if not node_mod._native_extract_available():
            print(json.dumps(
                {"ok": False, "error": "native extractor unavailable"}
            ))
            return
        net = BCH_REGTEST
        _progress(
            f"generating {n_blocks}-block chain x{txs_per_block} txs..."
        )
        all_blocks = gen_chain(
            net, n_blocks, txs_per_block, inputs_per_tx=inputs_per_tx,
            cache=(
                f"ibd_bench_{n_blocks}x{txs_per_block}"
                f"x{inputs_per_tx}.bin"
            ),
        )
        n_sigs = sum(
            len(tx.inputs) for b in all_blocks for tx in b.txs[1:]
        )

        async def sync_once(verify: bool, native: bool, store_dir: str,
                            blocks=None):
            blocks = all_blocks if blocks is None else blocks
            count = len(blocks)
            """One full planner-driven sync over a fresh LogKV store."""
            from tests.fakenet import dummy_peer_connect, poll_until

            os.environ["TPUNODE_UTXO_NATIVE"] = "1" if native else "0"
            saved = node_mod._native_extract_state
            if not native:
                # serial all-Python baseline: force the python extract
                # path too (the pre-native block ingest)
                node_mod._native_extract_state = False
            try:
                store = LogKV(os.path.join(store_dir, "kv.log"))
                pub = Publisher(name="bench-ibd", maxsize=None)
                cfg = NodeConfig(
                    net=net, store=store, pub=pub,
                    peers=["[::1]:18555"], discover=False,
                    connect=lambda sa: dummy_peer_connect(net, blocks),
                    verify=(
                        VerifyConfig(backend="cpu", max_wait=0.005)
                        if verify else None
                    ),
                    prevout_lookup=synth_prevout if verify else None,
                    utxo=True,
                    ibd=IbdConfig(batch_blocks=16, tick_interval=0.05),
                    extract_workers=(
                        0 if native else 1  # 0 = auto (min(4, cpu))
                    ),
                )
                verdicts = 0
                t0 = time.perf_counter()
                async with pub.subscription() as events:
                    async with Node(cfg) as node:
                        async def watch():
                            nonlocal verdicts
                            while True:
                                ev = await events.receive()
                                if isinstance(ev, TxVerdict):
                                    verdicts += 1
                        task = asyncio.ensure_future(watch())  # asyncsan: disable=raw-spawn (bench observer, cancelled below)
                        try:
                            await poll_until(
                                lambda: node.utxo.height == count,
                                timeout=600, what="ibd sync",
                            )
                            if verify:
                                total = count * (txs_per_block + 1)
                                await poll_until(
                                    lambda: verdicts >= total,
                                    timeout=120, what="all verdicts",
                                )
                        finally:
                            task.cancel()
                        dt = time.perf_counter() - t0
                        fetched = node.ibd.stats()["fetched_blocks"]
                store.close()
                sigs = sum(
                    len(tx.inputs) for b in blocks for tx in b.txs[1:]
                )
                return {
                    "wall_s": round(dt, 3),
                    "blocks_per_s": round(count / dt, 1),
                    "txs_per_s": round(
                        count * (txs_per_block + 1) / dt, 1
                    ),
                    "sigs_per_s": round(sigs / dt, 1) if verify else None,
                    "verdicts": verdicts,
                    "fetched_blocks": fetched,
                }
            finally:
                node_mod._native_extract_state = saved
                os.environ.pop("TPUNODE_UTXO_NATIVE", None)

        async def run_ab() -> dict:
            out: dict = {"ok": True, "proxy": "cpu-native",
                         "blocks": n_blocks, "txs_per_block": txs_per_block,
                         "inputs_per_tx": inputs_per_tx, "sigs": n_sigs}
            # untimed FULL-SIZE warmup: the first full-scale sync in a
            # process pays one-off costs (native lib loads, engine
            # warmup, allocator/heap growth at the working-set size)
            # that would otherwise be billed to whichever timed leg runs
            # first — a 40-block mini-warmup measurably does NOT cover
            # them (the first 300-block leg still ran ~4x slow)
            _progress("warmup sync (untimed, full size)...")
            d = tempfile.mkdtemp(prefix="ibd_warmup_")
            try:
                await sync_once(True, True, d)
            finally:
                shutil.rmtree(d, ignore_errors=True)
            legs = (
                # the ingest A/B runs twice per side, best kept: host-load
                # drift on a shared box swings a single pass ±30% (the
                # PERF r6 round-robin lesson, applied cheaply)
                ("ingest_native", True, True, 2,
                 "verify on, sharded native extract + C++ connect"),
                ("ingest_python", True, False, 2,
                 "verify on, serial python extract + python connect"),
                ("connect_native", False, True, 1,
                 "no verify: wire -> C++ one-pass UTXO connect"),
                ("connect_python", False, False, 1,
                 "no verify: wire -> python parse + connect"),
            )
            for key, verify, native, reps, note in legs:
                _progress(f"{key}: {note}...")
                best = None
                for _ in range(reps):
                    d = tempfile.mkdtemp(prefix=f"ibd_{key}_")
                    try:
                        leg = await sync_once(verify, native, d)
                    finally:
                        shutil.rmtree(d, ignore_errors=True)
                    if best is None or leg["wall_s"] < best["wall_s"]:
                        best = leg
                best["note"] = note
                best["runs"] = reps
                out[key] = best
            out["ingest_speedup"] = round(
                out["ingest_native"]["blocks_per_s"]
                / out["ingest_python"]["blocks_per_s"], 3,
            )
            out["connect_speedup"] = round(
                out["connect_native"]["blocks_per_s"]
                / out["connect_python"]["blocks_per_s"], 3,
            )
            # the acceptance ratio: block-ingest e2e, native vs the
            # serial Python-connect baseline in the same run
            out["speedup"] = out["ingest_speedup"]
            return out

        section = asyncio.run(run_ab())

        # -- kill -9 leg ----------------------------------------------------
        _progress(f"kill -9 leg: {kill_blocks}-block child sync...")
        d = tempfile.mkdtemp(prefix="ibd_kill9_")
        try:
            child_env = dict(
                os.environ,
                JAX_PLATFORMS="cpu",
                TPUNODE_IBD_CHILD_DIR=d,
                TPUNODE_IBD_CHILD_BLOCKS=str(kill_blocks),
            )
            def spawn():
                return subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--ibd-child"],
                    stdout=subprocess.PIPE, text=True, env=child_env,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
            # phase 1: kill mid-sync once the watermark passes ~40%
            p = spawn()
            killed_at = None
            deadline = time.monotonic() + 240
            for line in p.stdout:
                if time.monotonic() > deadline:
                    break
                if line.startswith("WM "):
                    wm = int(line.split()[1])
                    if wm >= kill_blocks * 2 // 5:
                        killed_at = wm
                        os.kill(p.pid, signal.SIGKILL)
                        break
                elif line.startswith("DONE"):
                    break  # synced before we could kill: still a result
            p.wait()
            if killed_at is None:
                section["kill9"] = {
                    "ok": False,
                    "error": "child finished before the kill window",
                }
            else:
                # phase 2: restart over the same store, run to completion
                p2 = spawn()
                report = None
                for line in p2.stdout:
                    if line.startswith("DONE "):
                        report = json.loads(line[5:])
                p2.wait()
                if report is None:
                    section["kill9"] = {
                        "ok": False, "error": "restart child died",
                    }
                else:
                    resumed = report["start_watermark"]
                    expected = (kill_blocks - resumed) * 2  # tx + coinbase
                    # "zero re-verification" is measured against the
                    # RESUMED watermark: a kill mid-write may lose the
                    # last un-synced record (torn tail, truncated on
                    # replay), but everything below the watermark the
                    # store DID resume from must cost nothing again.
                    section["kill9"] = {
                        "ok": (
                            resumed > 0
                            and report["final_watermark"] == kill_blocks
                            and report["verify_txs"] == expected
                            and report["fetched_blocks"]
                            == kill_blocks - resumed
                        ),
                        "killed_at_watermark": killed_at,
                        "resumed_from_watermark": resumed,
                        "final_watermark": report["final_watermark"],
                        "reverified_blocks": max(
                            0,
                            (report["verify_txs"] - expected) // 2,
                        ),
                        "refetched_blocks": max(
                            0,
                            report["fetched_blocks"]
                            - (kill_blocks - resumed),
                        ),
                    }
                    if not section["kill9"]["ok"]:
                        section["ok"] = False
                        section["error"] = "kill -9 leg failed"
        finally:
            shutil.rmtree(d, ignore_errors=True)
        print(json.dumps(section))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _worker_ibd_child() -> None:
    """The kill -9 leg's child: one planner-driven sync (verify engine on,
    cpu-native rung) over a persistent LogKV store in
    TPUNODE_IBD_CHILD_DIR, printing ``WM <height>`` as the watermark
    advances (the parent kills on this signal) and a final ``DONE
    {json}`` report.  Restarted over the same directory it resumes from
    the persisted watermark."""
    import asyncio

    from benchmarks.txgen import gen_chain, synth_prevout
    from tests.fakenet import dummy_peer_connect, poll_until
    from tpunode import (
        BCH_REGTEST, IbdConfig, Node, NodeConfig, Publisher,
    )
    from tpunode.metrics import metrics
    from tpunode.store import LogKV
    from tpunode.verify.engine import VerifyConfig

    d = os.environ["TPUNODE_IBD_CHILD_DIR"]
    n_blocks = int(os.environ["TPUNODE_IBD_CHILD_BLOCKS"])
    net = BCH_REGTEST
    blocks = gen_chain(
        net, n_blocks, 1, cache=f"ibd_kill_{n_blocks}x1.bin"
    )

    async def run():
        store = LogKV(os.path.join(d, "kv.log"), fsync=False)
        pub = Publisher(name="ibd-child", maxsize=None)
        cfg = NodeConfig(
            net=net, store=store, pub=pub,
            peers=["[::1]:18555"], discover=False,
            connect=lambda sa: dummy_peer_connect(net, blocks),
            verify=VerifyConfig(backend="cpu", max_wait=0.005),
            prevout_lookup=synth_prevout,
            utxo=True,
            ibd=IbdConfig(batch_blocks=16, tick_interval=0.05),
        )
        async with pub.subscription():
            async with Node(cfg) as node:
                start_wm = node.utxo.height
                last = [start_wm]

                async def report_progress():
                    while True:
                        wm = node.utxo.height
                        if wm != last[0]:
                            last[0] = wm
                            print(f"WM {wm}", flush=True)
                        await asyncio.sleep(0.01)

                task = asyncio.ensure_future(report_progress())  # asyncsan: disable=raw-spawn (child progress pipe, cancelled below)
                try:
                    await poll_until(
                        lambda: node.utxo.height == n_blocks,
                        timeout=600, what="child sync",
                    )
                finally:
                    task.cancel()
                print("DONE " + json.dumps({
                    "start_watermark": start_wm,
                    "final_watermark": node.utxo.height,
                    "verify_txs": int(metrics.get("node.verify_txs")),
                    "fetched_blocks": node.ibd.stats()["fetched_blocks"],
                }), flush=True)
        store.close()

    asyncio.run(run())


def _worker_kernel_ab() -> None:
    """Kernel formulation A/B worker: XLA step times on cpu-jax, in a
    bounded subprocess, cells timed ROUND-ROBIN so host-load drift hits
    every cell equally (the PERF r6 lesson: sequential per-process runs
    on this box swing ±75%).

    Two grids behind TPUNODE_BENCH_KERNELAB_MODE:

    * ``forms`` (default, ISSUE 8): projective vs affine point form.
    * ``reduce`` (ISSUE 12): the field_reduce x window_bits grid
      (eager/lazy x 4/5) at the default point form.

    Every cell compiles first (persistent cache) and cross-checks its
    verdicts against the C++ engine (a mismatch is FATAL — an A/B must
    never time a wrong program).  Prints one JSON line with
    median-of-N + spread per cell, like ``baseline_cpu_single_core``.
    """
    batch = int(os.environ.get("TPUNODE_BENCH_KERNELAB_BATCH", 1024))
    iters = int(os.environ.get("TPUNODE_BENCH_KERNELAB_ITERS", 5))
    mode = os.environ.get("TPUNODE_BENCH_KERNELAB_MODE", "forms")
    try:
        import jax
        import jax.numpy as jnp

        # this box's TPU shim force-sets jax_platforms in every process
        jax.config.update("jax_platforms", "cpu")
        from tpunode.verify.engine import enable_compile_cache

        enable_compile_cache()
        from benchmarks.common import make_triples, tile
        from tpunode.verify import curve as C
        from tpunode.verify import field as F
        from tpunode.verify import kernel as K
        from tpunode.verify.cpu_native import load_native_verifier
        from tpunode.verify.ecdsa_cpu import verify_batch_cpu
        from tpunode.verify.kernel import (
            collect_verdicts,
            prepare_batch,
            verify_device,
        )

        base = make_triples(min(UNIQUE, batch))
        items = tile(base, batch)
        native = load_native_verifier()
        expect = (
            native.verify_batch(base)
            if native is not None
            else verify_batch_cpu(base)
        )

        # (label, setter) per cell.  Args are prepared per cell: the
        # 5-bit cells carry 27-row digit arrays (and Python host prep).
        if mode == "reduce":
            def setter_for(red, wb):
                def set_modes():
                    F.set_field_modes(reduce=red)
                    K.set_kernel_modes(window_bits=wb)
                return set_modes

            cells = [
                (f"{red}@w{wb}", setter_for(red, wb))
                for red in ("eager", "lazy")
                for wb in (4, 5)
            ]
            delta_keys = ("lazy@w4", "eager@w4", "lazy_vs_eager")
        else:
            cells = [
                (form, (lambda f=form: C.set_point_form(f)))
                for form in ("projective", "affine")
            ]
            delta_keys = ("affine", "projective", "affine_vs_projective")
        stats: dict = {label: {"times": []} for label, _ in cells}
        cell_args: dict = {}
        for label, set_modes in cells:
            set_modes()
            prep = prepare_batch(items, pad_to=batch)
            cell_args[label] = tuple(
                jnp.asarray(a) for a in prep.device_args
            )
            _progress(f"compiling {label} XLA program at batch {batch}...")
            t0 = time.perf_counter()
            out = verify_device(*cell_args[label])
            got = collect_verdicts(out, len(base))
            stats[label]["compile_s"] = round(time.perf_counter() - t0, 1)
            if got != expect:
                print(
                    json.dumps(
                        {"ok": False, "fatal": True,
                         "error": f"{label}/oracle verdict mismatch"}
                    )
                )
                return
        for i in range(iters):
            _progress(f"timed round {i + 1}/{iters}...")
            for label, set_modes in cells:
                set_modes()
                t0 = time.perf_counter()
                verify_device(*cell_args[label]).block_until_ready()
                stats[label]["times"].append(time.perf_counter() - t0)
        section: dict = {
            "ok": True,
            "batch": batch,
            "proxy": "cpu-jax",
            "iters": iters,
            "mode": mode,
            "forms": {},
        }
        for label, _ in cells:
            ts = stats[label]["times"]
            section["forms"][label] = {
                "step_ms": round(statistics.median(ts) * 1e3, 1),
                "step_ms_min": round(min(ts) * 1e3, 1),
                "step_ms_max": round(max(ts) * 1e3, 1),
                "spread_rel": round(max(ts) / min(ts) - 1.0, 3),
                "compile_s": stats[label]["compile_s"],
            }
        a_key, b_key, delta_name = delta_keys
        a = section["forms"][a_key]["step_ms"]
        b = section["forms"][b_key]["step_ms"]
        section[delta_name] = round(a / b - 1.0, 4)
        print(json.dumps(section))
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}))


def _kernel_section() -> dict:
    """The BENCH JSON ``kernel`` section (ISSUE 8): projective-vs-affine
    step-time comparison per batch size, each in its own bounded worker
    so a timed-out cell is labeled without losing the others — and never
    masks the headline.  Batch 32768 is attempted only when its budget
    env is set (see T_KERNEL_AB_BIG)."""
    out: dict = {}
    for batch, budget in ((1024, T_KERNEL_AB), (32768, T_KERNEL_AB_BIG)):
        key = f"batch_{batch}"
        if budget <= 0:
            # per-batch reason: the big batch is disabled BY DEFAULT for
            # the compile-discipline reason; a small batch only gets
            # here when the operator zeroed its own knob (review r8 —
            # the 32768 rationale would be a false label there)
            out[key] = {
                "ok": False,
                "error": (
                    "disabled by default: cpu-jax XLA compile above "
                    "4096 violates the watchdog discipline and a 32768 "
                    "step is ~2 min — see PERF.md for the manual "
                    "no-watchdog A/B; set "
                    "TPUNODE_BENCH_KERNELAB_BIG_TIMEOUT to attempt"
                    if batch > 4096
                    else "disabled by operator: "
                    "TPUNODE_BENCH_KERNELAB_TIMEOUT <= 0"
                ),
            }
            continue
        res = _run_worker(
            "--kernel-ab", budget,
            {"JAX_PLATFORMS": "cpu",
             "TPUNODE_BENCH_KERNELAB_BATCH": str(batch)},
        )
        if not res.get("ok") and "error" in res:
            out[key] = {"ok": False, "error": str(res["error"])[:300]}
            if res.get("fatal"):
                out[key]["fatal"] = True
        else:
            out[key] = res
    # ISSUE 12: the field_reduce x window_bits grid at 1024, in its own
    # bounded worker so a timed-out grid is labeled without costing the
    # point-form cells (or the headline).
    if T_KERNEL_AB > 0:
        res = _run_worker(
            "--kernel-ab", T_KERNEL_AB * 2,
            {"JAX_PLATFORMS": "cpu",
             "TPUNODE_BENCH_KERNELAB_BATCH": "1024",
             "TPUNODE_BENCH_KERNELAB_MODE": "reduce"},
        )
        key = "reduce_window_batch_1024"
        if not res.get("ok") and "error" in res:
            out[key] = {"ok": False, "error": str(res["error"])[:300]}
            if res.get("fatal"):
                out[key]["fatal"] = True
        else:
            out[key] = res
    else:
        out["reduce_window_batch_1024"] = {
            "ok": False,
            "error": "disabled by operator: "
                     "TPUNODE_BENCH_KERNELAB_TIMEOUT <= 0",
        }
    return out


def _resilience_section() -> dict:
    """The BENCH JSON ``resilience`` section (ISSUE 7): failover count,
    breaker open/close transitions, verdict-conservation check and
    recovery latency from the seeded chaos scenario, measured in a
    bounded worker subprocess.  Always returns a dict — a failed/
    timed-out scenario is labeled, never masked (and never takes the
    headline down with it)."""
    res = _run_worker(
        "--chaos", T_CHAOS,
        # tunnel-independent: the device is simulated in-process
        {"JAX_PLATFORMS": "cpu"},
    )
    if not res.get("ok") and "error" in res:
        out = {"ok": False, "error": str(res["error"])[:300]}
        for k in ("verdict_conservation", "failovers", "breaker_opens",
                  "breaker_closes", "injections"):
            if k in res:
                out[k] = res[k]
        return out
    return res


def _recovery_section() -> dict:
    """The BENCH JSON ``recovery`` section (ISSUE 9): reopen/replay
    latency vs log size, compaction pause, and the kill-torture pass
    rate, measured in a bounded jax-free worker subprocess.  Always
    returns a dict — a failed/timed-out scenario is labeled, never
    masked (and never takes the headline down with it)."""
    res = _run_worker(
        "--recovery", T_RECOVERY,
        {"JAX_PLATFORMS": "cpu"},  # belt-and-braces: worker never imports jax
    )
    if not res.get("ok") and "error" in res:
        out = {"ok": False, "error": str(res["error"])[:300]}
        for k in ("replay", "compaction_pause_ms", "torture"):
            if k in res:
                out[k] = res[k]
        return out
    return res


def _pipeline_section() -> dict:
    """The BENCH JSON ``pipeline`` section (ISSUE 10): serial-vs-
    pipelined e2e throughput A/B, pack efficiency (mean lane occupancy),
    per-stage busy fractions and the extract-worker scaling curve, from
    a bounded worker subprocess on the cpu proxy.  Always returns a
    dict — a failed/timed-out scenario is labeled, never masked."""
    res = _run_worker(
        "--pipeline", T_PIPELINE,
        # cpu proxy by construction: backend="cpu" never imports jax;
        # the pin is belt-and-braces against future drift
        {"JAX_PLATFORMS": "cpu"},
    )
    if not res.get("ok") and "error" in res:
        out = {"ok": False, "error": str(res["error"])[:300]}
        for k in ("serial", "pipelined", "speedup",
                  "extract_scaling_txs_per_s"):
            if k in res:
                out[k] = res[k]
        return out
    return res


def _ibd_section() -> dict:
    """The BENCH JSON ``ibd`` section (ISSUE 11): long-IBD replay through
    the real fetch planner over the persistent store — blocks/s and
    sigs/s for the native-sharded vs serial-Python A/B (ingest with the
    cpu-native verify rung, plus the pure block-connect path), and the
    kill -9 mid-sync leg proving restart resumes from the watermark with
    zero re-verified blocks.  Always returns a dict — a failed/timed-out
    scenario is labeled, never masked (and never takes the headline
    down with it)."""
    res = _run_worker(
        "--ibd", T_IBD,
        # cpu proxy by construction: backend="cpu" never imports jax
        {"JAX_PLATFORMS": "cpu"},
    )
    if not res.get("ok") and "error" in res:
        out = {"ok": False, "error": str(res["error"])[:300]}
        for k in ("ingest_native", "ingest_python", "connect_native",
                  "connect_python", "speedup", "kill9"):
            if k in res:
                out[k] = res[k]
        return out
    return res


def _mesh_section() -> dict:
    """The BENCH JSON ``mesh`` section (ISSUE 13): fleet-dispatcher
    scaling at 1/2/4/8-way on the cpu-native proxy (acceptance floor
    0.8x ideal at 4-way) plus the campaign verdict bit-identity pass vs
    the single-chip path, from a bounded worker subprocess.  Always
    returns a dict — a failed/timed-out scenario is labeled, never
    masked (a campaign mismatch is additionally marked ``fatal`` so the
    driver exits nonzero, exactly like the headline's)."""
    res = _run_worker(
        "--mesh", T_MESH,
        # cpu proxy by construction: backend="cpu" never imports jax;
        # the pin is belt-and-braces against future drift
        {"JAX_PLATFORMS": "cpu"},
    )
    if not res.get("ok") and "error" in res:
        out = {"ok": False, "error": str(res["error"])[:300]}
        for k in ("ways", "scaling_at_4", "scaling_floor", "campaign",
                  "fatal"):
            if k in res:
                out[k] = res[k]
        return out
    return res


def _mesh_e2e_section() -> dict:
    """The BENCH JSON ``mesh_e2e`` section (ISSUE 19): host-affine vs
    central-feed e2e throughput at 4-way under a slow host (acceptance
    floor 1.25x the central baseline), per-host feed-idle starvation
    fractions for both legs, and the campaign verdict bit-identity pass
    through the affine path, from a bounded worker subprocess.  Always
    returns a dict — a failed/timed-out scenario is labeled, never
    masked (a campaign mismatch is additionally marked ``fatal`` so the
    driver exits nonzero, exactly like the headline's)."""
    res = _run_worker(
        "--mesh-e2e", T_MESH_E2E,
        # cpu proxy by construction: backend="cpu" never imports jax;
        # the pin is belt-and-braces against future drift
        {"JAX_PLATFORMS": "cpu"},
    )
    if not res.get("ok") and "error" in res:
        out = {"ok": False, "error": str(res["error"])[:300]}
        for k in ("central", "affine", "speedup", "speedup_floor",
                  "campaign", "fatal"):
            if k in res:
                out[k] = res[k]
        return out
    return res


def _serve_section() -> dict:
    """The BENCH JSON ``serve`` section (ISSUE 20): the multi-tenant
    firehose — per-class verdict-latency p50/p99, cache hit-rate, the
    verdict-conservation pin, the induced-burn shed leg (only bulk-class
    tenants shed; block-class p99 inside its SLO objective), and the
    receipt-log audit + per-append overhead — from a bounded worker
    subprocess.  Always returns a dict — a failed/timed-out scenario is
    labeled, never masked (a verdict divergence or conservation break is
    additionally marked ``fatal`` so the driver exits nonzero, exactly
    like the headline's)."""
    res = _run_worker(
        "--serve", T_SERVE,
        # cpu proxy by construction: backend="cpu" never imports jax;
        # the pin is belt-and-braces against future drift
        {"JAX_PLATFORMS": "cpu"},
    )
    if not res.get("ok") and "error" in res:
        out = {"ok": False, "error": str(res["error"])[:300]}
        for k in ("latency", "firehose", "burn_leg", "conservation",
                  "receipts", "fatal"):
            if k in res:
                out[k] = res[k]
        return out
    return res


def _mempool_section() -> dict:
    """The BENCH JSON ``mempool`` section: ingest efficiency from the
    duplicate-heavy fan-in scenario, measured in a bounded worker
    subprocess (the driver itself never imports jax).  Always returns a
    dict — a failed/timed-out scenario is labeled, never masked."""
    res = _run_worker(
        "--mempool", T_MEMPOOL,
        # never touch the device from this scenario: the oracle backend
        # plus a cpu-pinned jax keeps it tunnel-independent
        {"JAX_PLATFORMS": "cpu"},
    )
    if not res.get("ok") and "error" in res:
        return {"ok": False, "error": str(res["error"])[:300]}
    return res


def _worker_observability() -> None:
    """Observability-overhead micro-bench (ISSUE 16).

    Populates a realistic registry (~100 unlabeled series, an 8-host
    fleet's labeled gauges, a busy histogram), then measures: the
    timeline sampler's per-tick cost (median), the off-switch tick cost
    (must be ~an attribute read), and one flight-recorder bundle build.
    Never imports jax — timeseries/blackbox are stdlib-only by contract.
    """
    try:
        import statistics as _stats

        from tpunode.blackbox import FlightRecorder, FlightRecorderConfig
        from tpunode.metrics import metrics
        from tpunode.timeseries import Timeline

        from tpunode.verify.sched import host_names  # jax-free

        for i in range(100):
            metrics.inc("bench.obs_series_%d" % i, i + 1)
        for h, name in enumerate(host_names(8)):
            host = {"host": name}
            metrics.set_gauge("sched.host_depth", float(h), labels=host)
            metrics.set_gauge("verify.breaker_state", 0.0, labels=host)
            metrics.set_gauge("mesh.host_chips", 4.0, labels=host)
        for i in range(64):
            metrics.observe("verify.occupancy", (i % 20) / 20.0)

        def tick_median(tl: "Timeline", n: int = 300) -> float:
            xs = []
            for _ in range(n):
                t0 = time.perf_counter()
                tl.tick()
                xs.append(time.perf_counter() - t0)
            return _stats.median(xs)

        timeline = Timeline(interval=1.0, disabled=False)
        timeline.tick()  # warm the rings (first tick allocates deques)
        tick_s = tick_median(timeline)
        off = Timeline(interval=1.0, disabled=True)
        off_s = tick_median(off)

        recorder = FlightRecorder(
            FlightRecorderConfig(min_interval=0.0), timeline=timeline
        )
        t0 = time.perf_counter()
        bundle = recorder.record("bench.observability", force=True)
        build_ms = (time.perf_counter() - t0) * 1e3

        # SLO engine (ISSUE 17): evaluator tick cost (enabled + the
        # off-switch), and burn-detection latency — how many 1s ticks a
        # synthetic dispatch stall needs to page against a 100-tick
        # healthy baseline (deterministic: explicit now= timestamps).
        from tpunode.events import EventLog
        from tpunode.slo import SloEvaluator

        def slo_tick_median(ev, base: float, n: int = 300) -> float:
            xs = []
            for i in range(n):
                t0 = time.perf_counter()
                ev.tick(now=base + i)
                xs.append(time.perf_counter() - t0)
            return _stats.median(xs)

        slo_tick_s = slo_tick_median(
            SloEvaluator(registry=metrics, log_=EventLog(), disabled=False),
            base=1_000.0,
        )
        slo_off_s = slo_tick_median(
            SloEvaluator(defs=None, registry=metrics, log_=EventLog()),
            base=2_000.0,
        )
        det_log = EventLog()
        det = SloEvaluator(registry=metrics, log_=det_log, disabled=False)
        for i in range(100):
            det.tick(now=50_000.0 + i)  # healthy baseline
        metrics.set_gauge("watchdog.stalled", 1.0)  # the wedged dispatch
        det_ticks = 0
        for i in range(100, 400):
            det.tick(now=50_000.0 + i)
            det_ticks += 1
            if det_log.counts().get("slo.burn"):
                break
        metrics.set_gauge("watchdog.stalled", 0.0)

        print(
            json.dumps(
                {
                    "ok": True,
                    "sampler": {
                        "tick_us_p50": round(tick_s * 1e6, 2),
                        "disabled_tick_us_p50": round(off_s * 1e6, 4),
                        "series": timeline.stats()["series"],
                    },
                    "blackbox": {
                        "build_ms": round(build_ms, 3),
                        "bundle_keys": sorted(bundle or {}),
                    },
                    "slo": {
                        "tick_us_p50": round(slo_tick_s * 1e6, 2),
                        "disabled_tick_us_p50": round(slo_off_s * 1e6, 4),
                        "burn_detection": {
                            "ticks": det_ticks,
                            "seconds": round(det_ticks * det.interval, 1),
                        },
                    },
                }
            )
        )
    except Exception as e:  # noqa: BLE001 — worker reports, parent decides
        print(
            json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"[:500]})
        )


def _observability_section() -> dict:
    """The BENCH JSON ``observability`` section (ISSUE 16): sampler tick
    cost (enabled + off-switch) and flight-recorder bundle build time
    from a bounded, jax-free worker subprocess.  Always returns a dict —
    a failed/timed-out scenario is labeled, never masked."""
    res = _run_worker("--observability", T_OBS, {"JAX_PLATFORMS": "cpu"})
    if not res.get("ok") and "error" in res:
        return {"ok": False, "error": str(res["error"])[:300]}
    return res


def _run_worker(
    mode: str, timeout: float, env_extra: dict | None = None
) -> dict:
    """Run a bench worker subprocess via the shared group-kill runner."""
    from benchmarks.common import run_json_subprocess

    return run_json_subprocess(
        [sys.executable, os.path.abspath(__file__), mode],
        timeout,
        env_extra,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )


DEVICE_RUNS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "benchmarks", "device_runs.jsonl"
)
# Only trust same-round watcher samples.  The watcher truncates the file
# at startup (one rotation per round); the age cap is belt-and-braces for
# a round whose watcher never launched over a committed previous-round
# file (rounds are ~12 h apart, so a cross-round row is always older).
DEVICE_RUN_MAX_AGE = 12 * 3600


def _freshest_device_run(path: str = DEVICE_RUNS) -> dict | None:
    """Freshest in-round TPU headline sample from the round-long watcher
    (benchmarks/watcher.py, VERDICT r4 item 1).  The watcher appends one
    JSON line per successful device measurement; this returns the newest
    valid ``kind == "headline"`` row on a tpu device, or None.

    A recorded ``kind == "fatal"`` row (device/oracle verdict mismatch)
    poisons the whole file: correctness failures must never be masked by
    an earlier-or-later passing sample, so the fallback is disabled for
    the round.  Rows that are valid JSON but corrupt (partial writes,
    missing/non-numeric fields) are skipped — main() must always emit its
    one JSON line.
    """
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return None
    best: dict | None = None
    now = time.time()
    for line in lines:
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(row, dict):
            continue
        if row.get("kind") == "fatal":
            return None
        if row.get("kind") != "headline":
            continue
        if not str(row.get("device", "")).startswith("tpu"):
            continue
        if not isinstance(row.get("value"), (int, float)) or not isinstance(
            row.get("unix"), (int, float)
        ) or not isinstance(row.get("ts"), str):
            continue
        if now - row["unix"] > DEVICE_RUN_MAX_AGE:
            continue
        if best is None or row["unix"] > best["unix"]:
            best = row
    return best


def _watcher_evidence(log_path: str | None = None) -> dict | None:
    """Compact in-artifact summary of the round-long watcher's probe log.

    When the live attempt fails, the one JSON line should carry the
    tunnel-availability evidence itself (VERDICT r4 item 1: a round with
    zero device samples must prove the tunnel never came up) instead of
    pointing at a log the judge has to dig out of git.  Parses the
    freshest ``benchmarks/watcher*.log``, keeps only in-round lines
    (same age cap as the device samples, and — since the log is
    append-shared across rounds — only from the first in-window
    ``watcher up`` launch on, so a prior round's tail can't inflate this
    round's availability), and reports probe totals plus the last time
    the tunnel was seen up, or None when no watcher ever logged this
    round.  Never raises: evidence is best-effort garnish on an
    already-failing path, and main()'s one-JSON-line invariant wins.
    """
    bench_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"
    )
    try:
        if log_path is None:
            logs = [
                os.path.join(bench_dir, f)
                for f in os.listdir(bench_dir) if _WATCHER_LOG_RE.match(f)
            ] if os.path.isdir(bench_dir) else []
            if not logs:
                return None
            log_path = max(logs, key=os.path.getmtime)
        # errors="replace": the live watcher appends concurrently, and a
        # torn multi-byte write must not raise UnicodeDecodeError here
        with open(log_path, encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return None
    now = time.time()
    parsed = []
    for line in lines:
        m = _WATCHER_LINE_RE.match(line)
        if not m:
            continue
        try:
            unix = calendar.timegm(
                time.strptime(m.group(1), "%Y-%m-%dT%H:%M:%SZ")
            )
        except ValueError:
            continue
        if now - unix > DEVICE_RUN_MAX_AGE:
            continue
        parsed.append((m.group(1), m.group(2)))
    # This round's watcher launches at round start, so its first
    # in-window launch line is the round boundary; launches == 0 in the
    # output means no round-start watcher ran (itself evidence).
    for i, (_, msg) in enumerate(parsed):
        if msg.startswith("watcher up"):
            parsed = parsed[i:]
            break
    probes = up = launches = 0
    first_ts = last_ts = last_up = None
    for ts, msg in parsed:
        if msg.startswith("watcher up"):
            launches += 1
            continue
        if "probe #" not in msg:
            continue
        probes += 1
        if first_ts is None:
            first_ts = ts
        last_ts = ts
        if "TPU UP" in msg:
            up += 1
            last_up = ts
    if probes == 0 and launches == 0:
        return None
    return {
        "log": os.path.relpath(log_path, os.path.dirname(bench_dir)),
        "launches": launches,
        "probes": probes,
        "up_probes": up,
        "first_probe": first_ts,
        "last_probe": last_ts,
        "last_up": last_up,
    }


_WATCHER_LOG_RE = re.compile(r"^watcher.*\.log$")
_WATCHER_LINE_RE = re.compile(r"^\[(\d{4}-\d\d-\d\dT\d\d:\d\d:\d\dZ)\] (.*)")


BENCH_LOCK = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "benchmarks", ".bench_running"
)


def main() -> None:
    # Tunnel clients block each other: the round-long watcher pauses its
    # probing while this lock exists so the driver's round-end bench gets
    # the device to itself (watcher ignores locks older than 30 min in
    # case a bench dies without cleanup).
    try:
        with open(BENCH_LOCK, "w", encoding="utf-8") as f:
            f.write(f"{os.getpid()} {time.time()}\n")
    except OSError:
        pass
    try:
        _main_locked()
    finally:
        try:
            os.remove(BENCH_LOCK)
        except OSError:
            pass


def _main_locked() -> None:
    # CPU single-core baseline first: jax-free, can't hang on TPU init.
    # Median of 5 timed passes with the spread recorded (VERDICT r5 weak
    # #7: a single pass drifted vs_baseline ±25% with host load).
    from benchmarks.common import cpu_single_core_stats, make_triples

    base = make_triples(UNIQUE)
    cpu_stats = cpu_single_core_stats(base[:CPU_SAMPLE])
    cpu_rate, cpu_engine = cpu_stats["rate"], cpu_stats["engine"]

    attempts: list[str] = []
    res: dict = {"ok": False, "error": "no attempt ran"}

    probe = _run_worker("--probe", T_PROBE)
    if probe.get("ok") and probe.get("platform") == "tpu":
        # Every worker re-pays backend init; a slow-but-alive tunnel must not
        # eat the compile budget, so stretch each rung by the measured
        # init time (capped — a 2-minute init still leaves the ladder
        # inside the driver's overall tolerance).
        extra = min(180.0, float(probe.get("init_s", 0.0)) * 1.5)
        ladder = tuple((b, t + extra, k) for b, t, k in LADDER)
    else:
        # Dead/slow tunnel: one last-chance small-batch attempt (the probe
        # itself may have nudged the relay awake), then the cpu fallback.
        # If the in-round watcher banked its headline via the XLA kernel
        # (Mosaic outage), aim the last chance at the known-working path.
        attempts.append(
            "probe: "
            + str(probe.get("error") or f"platform={probe.get('platform')}")
        )
        hint = _freshest_device_run()
        kern = "xla" if (hint and hint.get("kernel") == "xla") else None
        ladder = ((4096, 150.0, kern),)
    from benchmarks.common import worker_rung_env

    # Hard ceiling on total ladder time: however many rungs fail slowly,
    # the cpu/watcher fallback still runs and the one JSON line still
    # prints inside the driver's tolerance (see module docstring).
    ladder_deadline = time.monotonic() + T_LADDER_TOTAL
    rungs = list(ladder)
    while rungs:
        batch, budget, kernel = rungs.pop(0)
        remaining = ladder_deadline - time.monotonic()
        if remaining < 60:
            attempts.append("ladder budget exhausted")
            break
        env, label = worker_rung_env(batch, kernel)
        res = _run_worker("--worker", min(budget, remaining), env)
        attempts.append(
            f"{label}: " + ("ok" if res.get("ok") else res.get("error", "?"))
        )
        if res.get("ok") or res.get("fatal"):
            break
        err = str(res.get("error", ""))
        if "initializing backend" in err or "probing backend" in err:
            # jax.devices() blocked for the rung's whole budget after a
            # live probe: the tunnel closed under us — stop burning the
            # remaining rungs and let the watcher/cpu fallback report.
            attempts.append("tunnel lost mid-ladder")
            break
        if kernel is None and ("MosaicError" in err or "timed out" in err):
            # Compile helper is rejecting pallas programs outright (HTTP
            # 500) or hanging on them (both observed r5) while plain XLA
            # works: any post-init pallas timeout means skip the doomed
            # pallas rungs and spend the remaining budget on the XLA
            # fallback rungs instead.
            rungs = [r for r in rungs if r[2] == "xla"]

    tpu_err = None
    provenance = "live"
    watcher_run = None
    if not res.get("ok") and not res.get("fatal"):
        tpu_err = res.get("error", "?")
        # Round-long watcher fallback (VERDICT r4 item 1): the bench only
        # samples at round end, but benchmarks/watcher.py samples all
        # round and persists every successful device measurement.  A
        # down-tunnel-at-bench-time round still reports a dated, in-round
        # TPU number with explicit provenance instead of a cpu rate.
        watcher_run = _freshest_device_run()
        if watcher_run is not None:
            provenance = "in-round-watcher"
            res = {
                "ok": True,
                "rate": watcher_run["value"],
                "device": watcher_run["device"],
                "kernel": watcher_run.get("kernel"),
                "batch": watcher_run.get("batch"),
                "step_ms": watcher_run.get("step_ms"),
                "compile_s": watcher_run.get("compile_s"),
                "init_s": watcher_run.get("init_s"),
            }
            attempts.append(f"watcher: ok @ {watcher_run['ts']}")
        else:
            # Clearly-labeled cpu-jax fallback so the driver still records
            # a numeric value; ``device`` says cpu:* and tpu_error says why.
            res = _run_worker(
                "--worker",
                T_FALLBACK,
                {
                    "JAX_PLATFORMS": "cpu",
                    "TPUNODE_BENCH_FORCE_CPU": "1",
                    "TPUNODE_BENCH_BATCH": "2048",
                    "TPUNODE_BENCH_ITERS": "2",
                },
            )
            attempts.append(
                "cpu-fallback: "
                + ("ok" if res.get("ok") else res.get("error", "?"))
            )
            # provenance only claims a source that produced the number
            provenance = "cpu-fallback" if res.get("ok") else "none"

    out = {
        "metric": "sig_verify_throughput",
        "value": round(res.get("rate", 0.0), 1),
        "unit": "sigs/sec/chip",
        "vs_baseline": round(res.get("rate", 0.0) / cpu_rate, 2),
        "device": res.get("device", "unavailable"),
        "provenance": provenance,
        "baseline_cpu_single_core": round(cpu_rate, 1),
        "baseline_cpu_runs": cpu_stats["runs"],
        "baseline_cpu_spread": {
            "min": round(cpu_stats["rate_min"], 1),
            "max": round(cpu_stats["rate_max"], 1),
            "rel": round(cpu_stats["rate_spread"], 3),
        },
        "baseline_engine": cpu_engine,
        "attempts": "; ".join(attempts),
    }
    if tpu_err is not None:
        out["tpu_error"] = tpu_err
        # The artifact itself proves what the tunnel did all round
        # (probe totals + last-seen-up), not just what it did at bench
        # time — a zero-device-sample round is then self-evidencing.
        evidence = _watcher_evidence()
        if evidence is not None:
            out["watcher_evidence"] = evidence
    if watcher_run is not None:
        out["measured_at"] = watcher_run["ts"]
        out["measured_age_s"] = int(time.time() - watcher_run["unix"])
    for k in ("kernel", "batch", "step_ms", "compile_s", "init_s", "error",
              "profile_path"):
        if k in res and res[k] is not None:
            out[k] = res[k]
    if probe.get("init_s") is not None:
        out["probe_init_s"] = probe["init_s"]
    # Telemetry section (span percentiles, occupancy histogram, event
    # counts): normally measured inside the successful worker; when the
    # number came from the watcher/fallback paths, fall back to this
    # process's registry so the section is always present and labeled.
    tel = res.get("telemetry")
    if not isinstance(tel, dict):
        from tpunode.metrics import metrics as _metrics

        tel = _metrics.telemetry()
        tel["source"] = "driver-local"
    out["telemetry"] = tel
    # Slowest causal traces (tracectx): measured in the worker alongside
    # the telemetry section; the fallback paths report the driver's own
    # (normally empty) ring so the key is always present.
    st = res.get("slowest_traces")
    if not isinstance(st, list):
        from tpunode.tracectx import tracer as _tracer

        st = _tracer.slowest(3)
    out["slowest_traces"] = st
    # asyncsan sanitizer counts (task leaks, watchdog stalls): from the
    # worker when it ran, else this process's registries — always present
    # so the round-over-round trajectory catches concurrency regressions.
    san = res.get("sanitizers")
    if not isinstance(san, dict):
        from tpunode.events import events as _events2
        from tpunode.metrics import metrics as _metrics2

        san = _sanitizer_counts(_events2.counts(), _metrics2)
        san["source"] = "driver-local"
    out["sanitizers"] = san
    # Mempool ingest-efficiency section (ISSUE 5): dedup hit-rate,
    # admission p50/p99 and orphan resolutions from the duplicate-heavy
    # fan-in scenario, so the trajectory tracks what the node does with
    # redundant gossip — not just raw kernel sigs/s.
    out["mempool"] = _mempool_section()
    # Streaming-pipeline section (ISSUE 10): serial-vs-pipelined e2e
    # sigs/s, pack efficiency, stage busy fractions and the
    # extract-worker scaling curve on the cpu proxy — failure-labeled
    # like the sections below so it never masks the headline.
    out["pipeline"] = _pipeline_section()
    # Resilience section (ISSUE 7): failover/breaker behavior under a
    # seeded fault plan — verdict conservation, breaker open/close
    # transitions and recovery latency, failure-labeled like the
    # mempool section so it never masks the headline.
    out["resilience"] = _resilience_section()
    # Crash-recovery section (ISSUE 9): reopen/replay latency vs log
    # size, compaction pause, kill-torture pass-rate — recovery cost as
    # a tracked number, failure-labeled like the sections above.
    out["recovery"] = _recovery_section()
    # Long-IBD section (ISSUE 11): fetch-planner-driven block ingest A/B
    # (native sharded + C++ connect vs serial Python) and the kill -9
    # resume leg — failure-labeled like the sections above.
    out["ibd"] = _ibd_section()
    # Pod-scale mesh section (ISSUE 13): fleet-dispatcher scaling at
    # 1/2/4/8-way on the cpu-native proxy (>= 0.8x ideal at 4-way) and
    # the campaign bit-identity pass — failure-labeled like the others.
    out["mesh"] = _mesh_section()
    # Host-affine feed section (ISSUE 19): affine vs central-feed e2e
    # throughput at 4-way under a slow host (>= 1.25x the central
    # baseline), per-host feed-idle fractions, and the campaign
    # bit-identity pass through the affine path — failure-labeled like
    # the others.
    out["mesh_e2e"] = _mesh_e2e_section()
    # Kernel point-form A/B section (ISSUE 8): projective vs affine step
    # time on cpu-jax, failure-labeled per batch like the sections above.
    # Named "kernel_ab" because the top-level "kernel" key already names
    # the program (pallas/xla) that produced the headline.
    out["kernel_ab"] = _kernel_section()
    # Observability-overhead section (ISSUE 16): timeline sampler tick
    # cost (on + off-switch) and flight-recorder bundle build cost, so
    # the retrospective stack's overhead is a tracked number —
    # failure-labeled like the others.
    out["observability"] = _observability_section()
    # Multi-tenant serve section (ISSUE 20): the firehose + shed +
    # receipt-audit acceptance — failure-labeled like the others.
    out["serve"] = _serve_section()
    print(json.dumps(out))
    # A fatal anywhere is a kernel correctness failure (device/oracle or
    # affine/oracle verdict mismatch) and must not look like success —
    # the A/B section's fatal counts exactly like the headline's.
    kab_fatal = any(
        isinstance(cell, dict) and cell.get("fatal")
        for cell in out["kernel_ab"].values()
    )
    if (
        res.get("fatal")
        or kab_fatal
        or out["mesh"].get("fatal")
        or out["mesh_e2e"].get("fatal")
        or out["serve"].get("fatal")
    ):
        sys.exit(1)


if __name__ == "__main__":
    if "--worker" in sys.argv:
        _worker_bench()
    elif "--probe" in sys.argv:
        _worker_probe()
    elif "--mempool" in sys.argv:
        _worker_mempool()
    elif "--chaos" in sys.argv:
        _worker_chaos()
    elif "--recovery" in sys.argv:
        _worker_recovery()
    elif "--kernel-ab" in sys.argv:
        _worker_kernel_ab()
    elif "--pipeline" in sys.argv:
        _worker_pipeline()
    elif "--ibd-child" in sys.argv:
        _worker_ibd_child()
    elif "--ibd" in sys.argv:
        _worker_ibd()
    elif "--mesh-device" in sys.argv:
        _worker_mesh_device()
    elif "--mesh-e2e" in sys.argv:
        _worker_mesh_e2e()
    elif "--serve" in sys.argv:
        _worker_serve()
    elif "--mesh" in sys.argv:
        _worker_mesh()
    elif "--observability" in sys.argv:
        _worker_observability()
    else:
        main()
