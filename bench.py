"""Driver benchmark: batch ECDSA verify throughput on one chip.

Measures the north-star metric (BASELINE.json): sig-verifies/sec/chip of
the TPU kernel at the standard batch size (4096), against the single-core
CPU baseline (the C++ batch verifier in native/secp256k1, the stand-in for
single-core libsecp256k1).  Prints exactly ONE JSON line:

    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

Run from the repo root: python bench.py
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

BATCH = int(os.environ.get("TPUNODE_BENCH_BATCH", 4096))
UNIQUE = min(512, BATCH)  # unique sigs, tiled to BATCH (device work identical)
TIMED_ITERS = 5
CPU_SAMPLE = min(256, BATCH)


def make_items(n: int):
    from benchmarks.common import make_triples

    return make_triples(n)


def bench_device(items) -> tuple[float, str]:
    """Steady-state device throughput (sigs/sec) and device kind."""
    import jax
    import jax.numpy as jnp

    from tpunode.verify.ecdsa_cpu import verify_batch_cpu
    from tpunode.verify.kernel import prepare_batch, verify_device

    dev = jax.devices()[0]
    prep = prepare_batch(items, pad_to=BATCH)
    args = tuple(
        jax.device_put(jnp.asarray(a), dev) for a in prep.device_args
    )
    out = verify_device(*args)  # compile + first run
    got = [bool(b) for b in out][: len(items)]
    expect = verify_batch_cpu(items)
    if got != expect:
        print(
            json.dumps({"error": "device/oracle verdict mismatch"}),
            file=sys.stderr,
        )
        sys.exit(1)

    from tpunode.trace import profile_to

    times = []
    with profile_to(os.environ.get("TPUNODE_PROFILE")):
        for _ in range(TIMED_ITERS):
            t0 = time.perf_counter()
            verify_device(*args).block_until_ready()
            times.append(time.perf_counter() - t0)
    dt = statistics.median(times)
    from benchmarks.common import device_kind

    return BATCH / dt, device_kind()


def bench_cpu_single_core(items) -> float:
    """Single-core baseline (sigs/sec): C++ verifier, oracle fallback."""
    from benchmarks.common import cpu_single_core_rate

    return cpu_single_core_rate(items[:CPU_SAMPLE])


def main() -> None:
    base_items = make_items(UNIQUE)
    from benchmarks.common import tile

    items = tile(base_items, BATCH)
    cpu_rate = bench_cpu_single_core(base_items)
    tpu_rate, device = bench_device(items)
    print(
        json.dumps(
            {
                "metric": "sig_verify_throughput",
                "value": round(tpu_rate, 1),
                "unit": "sigs/sec/chip",
                "vs_baseline": round(tpu_rate / cpu_rate, 2),
                "device": device,
                "baseline_cpu_single_core": round(cpu_rate, 1),
                "batch": BATCH,
            }
        )
    )


if __name__ == "__main__":
    main()
